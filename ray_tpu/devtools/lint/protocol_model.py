"""Declarative wire-protocol model: session DFAs + payload schemas.

One model, three consumers:

  * the **protocol-order** static pass (protocol_order.py) — every send
    site's constant must be a legal transition from the states its
    enclosing function is registered to run in, every request constant
    must have a registered response path, and no send may be reachable
    after the connection's teardown;
  * the **payload-schema** static pass (payload_schema.py) — send-site
    payload shapes are diffed against :data:`PAYLOADS` (orphan keys,
    phantom consumer reads, compact-tuple arity drift);
  * the **runtime conformance tap** (``_private/wiretap.py``) — live
    frame sequences are replayed through :class:`SessionDFA` instances
    per connection (RAY_TPU_WIRETAP=1).

This module is pure data + a pure-stdlib DFA interpreter: the runtime
MAY import it (wiretap does, lazily, only when enabled); nothing here
imports the runtime. New planes from the roadmap (direct object
transfer, compiled DAGs) register their sessions/constants HERE on day
one — an unmodeled constant is itself a protocol-order violation.

DFA notation (docs/STATIC_ANALYSIS.md#the-protocol-model): a *session*
is one logical conversation over one transport (the worker pipe, the
daemon TCP link, a brokered direct channel). Each session declares its
states, the initial state, per-role send tables (``CONST -> states in
which sending it is legal``), the handshake constants (first frame(s)
of the session, ``advance`` moves the DFA forward when one is seen),
and the teardown constant (after which the connection is CLOSED and any
further frame is a violation). Constants may belong to several sessions
— the direct channel's handshake (CHANNEL_REQ/CHANNEL_ADDR) rides the
worker pipe, so those constants appear in both the "worker" session
(plane membership) and the "direct" session (handshake states).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# sessions
# ---------------------------------------------------------------------------
# Three sessions cover the five parsed planes: "worker" carries
# to_worker + from_worker (one pipe, two directions), "daemon" carries
# head_to_daemon + daemon_to_head, "direct" carries the worker<->worker
# channel plane (actor calls, streams, AND the serve data plane — serve
# frames ride brokered DirectPlane connections).
SESSIONS = {
    "worker": {
        # head <-> worker pipe. No handshake (the fork/spawn plumbing
        # IS the establishment); SHUTDOWN is the head-side teardown.
        "states": ("OPEN", "CLOSED"),
        "initial": "OPEN",
        "handshake": (),
        "advance": {},
        "teardown": "SHUTDOWN",
        "roles": {
            "head": {
                "sends": {
                    "EXEC_TASK": ("OPEN",), "EXEC_TASKS": ("OPEN",),
                    "CREATE_ACTOR": ("OPEN",), "CANCEL_TASK": ("OPEN",),
                    "RELEASE_OBJECTS": ("OPEN",), "SHUTDOWN": ("OPEN",),
                    "REPLY": ("OPEN",), "CHANNEL_OPEN": ("OPEN",),
                    "RESULT_FWD": ("OPEN",), "SEQ_SETTLED": ("OPEN",),
                    "TELEMETRY_DRAIN": ("OPEN",),
                    "RECALL_QUEUED": ("OPEN",),
                },
            },
            "worker": {
                "sends": {
                    "REF_COUNT": ("OPEN",), "TASK_DONE": ("OPEN",),
                    "TASKS_DONE": ("OPEN",), "TASKS_RECALLED": ("OPEN",),
                    "GEN_ITEM": ("OPEN",), "ACTOR_READY": ("OPEN",),
                    "OWNED_PUT": ("OPEN",), "GET_LOCATIONS": ("OPEN",),
                    "WAIT_OBJECTS": ("OPEN",), "SUBMIT_TASK": ("OPEN",),
                    "SUBMIT_ACTOR_TASK": ("OPEN",),
                    "CREATE_ACTOR_REQ": ("OPEN",), "GET_ACTOR": ("OPEN",),
                    "KILL_ACTOR": ("OPEN",), "GCS_REQUEST": ("OPEN",),
                    "PULL_OBJECT": ("OPEN",), "TASK_EVENTS": ("OPEN",),
                    "METRICS_PUSH": ("OPEN",), "CHANNEL_REQ": ("OPEN",),
                    "CHANNEL_ADDR": ("OPEN",), "DIRECT_DONE": ("OPEN",),
                    "DIRECT_RECONCILE": ("OPEN",),
                    "REF_DELTAS": ("OPEN",),
                    "WORKER_BLOCKED": ("OPEN",),
                    "WORKER_UNBLOCKED": ("OPEN",),
                },
            },
        },
        # req_id-keyed REPLY pairing: outstanding requests are fed by
        # the Worker.request chokepoint (wiretap.request_sent); a REPLY
        # arriving for a req_id never sent is a violation.
        "rid_resp": "REPLY",
        # WORKER_BLOCKED/UNBLOCKED is a counter, not an alternation:
        # with max_concurrency > 1 several blocks overlap legally, but
        # the count may never dip negative.
        "counters": ({"up": "WORKER_BLOCKED", "down": "WORKER_UNBLOCKED"},),
        "pairs": (),
        "streams": None,
        "frees": None,
    },
    "daemon": {
        # head <-> node-daemon TCP link. REGISTER_NODE opens, NODE_ACK
        # confirms (strictly before any routed frame), SHUTDOWN_NODE
        # tears down.
        "states": ("NEW", "REGISTERED", "CLOSED"),
        "initial": "NEW",
        "handshake": ("REGISTER_NODE", "NODE_ACK"),
        "advance": {"REGISTER_NODE": "REGISTERED",
                    "NODE_ACK": "REGISTERED"},
        "teardown": "SHUTDOWN_NODE",
        "roles": {
            "head": {
                "sends": {
                    "NODE_ACK": ("NEW",),
                    "NODE_SYNC": ("REGISTERED",),
                    "START_WORKER": ("REGISTERED",),
                    "TO_WORKER": ("REGISTERED",),
                    "KILL_WORKER": ("REGISTERED",),
                    "WORKER_DEDICATED": ("REGISTERED",),
                    "SHUTDOWN_NODE": ("REGISTERED",),
                    "LOCALIZE_OBJECT": ("REGISTERED",),
                    "DRAIN_NODE": ("REGISTERED",),
                    "NODE_REPLY": ("REGISTERED",),
                    # release broadcasts fan out to daemons too (each
                    # relays to its own workers and frees its store)
                    "RELEASE_OBJECTS": ("REGISTERED",),
                },
            },
            "daemon": {
                "sends": {
                    "REGISTER_NODE": ("NEW",),
                    "NODE_PING": ("REGISTERED",),
                    "NODE_REQUEST": ("REGISTERED",),
                    "NODE_REPLY": ("REGISTERED",),
                    "FROM_WORKER": ("REGISTERED",),
                    "WORKER_DIED": ("REGISTERED",),
                    "DRAIN_STATUS": ("REGISTERED",),
                },
            },
        },
        "rid_resp": None,
        "counters": (),
        "pairs": (),
        "streams": None,
        "frees": None,
    },
    "direct": {
        # Brokered worker<->worker channel: actor calls, generator
        # streams, and the serve data plane. The handshake constants
        # ride the worker pipe (brokered establishment), so a live
        # channel object starts at OPEN (runtime_initial); the static
        # states still model handshake-before-call. DIRECT_RECONCILE
        # (also pipe-borne) is the caller's channel-death drain: it
        # settles every outstanding call that will never see its
        # ACTOR_RESULT.
        "states": ("ESTABLISHING", "OPEN", "DRAINING"),
        "initial": "ESTABLISHING",
        "runtime_initial": "OPEN",
        "handshake": ("CHANNEL_REQ", "CHANNEL_ADDR"),
        "advance": {"CHANNEL_REQ": "ESTABLISHING", "CHANNEL_ADDR": "OPEN",
                    "DIRECT_RECONCILE": "DRAINING"},
        "teardown": None,
        "roles": {
            "caller": {
                "sends": {
                    "CHANNEL_REQ": ("ESTABLISHING",),
                    "ACTOR_CALL": ("OPEN",),
                    "GEN_CANCEL": ("OPEN",),
                    "SERVE_REQ": ("OPEN",),
                    "SERVE_BODY_FREE": ("OPEN",),
                    "PULL_DIRECT": ("OPEN",),
                    "DIRECT_RECONCILE": ("DRAINING",),
                },
            },
            "callee": {
                "sends": {
                    "CHANNEL_ADDR": ("ESTABLISHING",),
                    "ACTOR_RESULT": ("OPEN", "DRAINING"),
                    "GEN_ITEM": ("OPEN", "DRAINING"),
                    "SERVE_RESP": ("OPEN", "DRAINING"),
                    "SERVE_BODY_FREE": ("OPEN", "DRAINING"),
                    "OBJ_CHUNK": ("OPEN", "DRAINING"),
                    "OBJ_EOF": ("OPEN", "DRAINING"),
                },
            },
        },
        "rid_resp": None,
        "counters": (),
        # Every ACTOR_CALL pairs with exactly one ACTOR_RESULT (or the
        # reconcile drain); SERVE_REQ rid-pairs with SERVE_RESP;
        # PULL_DIRECT rid-pairs with its OBJ_EOF terminal.
        "pairs": ({"req": "ACTOR_CALL", "resp": "ACTOR_RESULT"},
                  {"req": "SERVE_REQ", "resp": "SERVE_RESP"},
                  {"req": "PULL_DIRECT", "resp": "OBJ_EOF"}),
        # Stream specs (one or many): GEN_ITEM streams carry a dense
        # per-call index between the opening (streaming) ACTOR_CALL and
        # its terminal ACTOR_RESULT, with GEN_CANCEL moving the stream
        # to a draining set where late in-flight items stay legal.
        # OBJ_CHUNK streams are the object-transfer plane's ranged
        # chunks: gapless dense indexes between the opening PULL_DIRECT
        # and its OBJ_EOF terminal (no cancel — a dropped pull just
        # abandons the rid and the chunks drop on arrival).
        "streams": ({"item": "GEN_ITEM", "cancel": "GEN_CANCEL",
                     "opener": "ACTOR_CALL", "terminal": "ACTOR_RESULT"},
                    {"item": "OBJ_CHUNK", "cancel": None,
                     "opener": "PULL_DIRECT", "terminal": "OBJ_EOF"}),
        # SERVE_BODY_FREE only for a body the peer actually staged.
        "frees": {"free": "SERVE_BODY_FREE",
                  "stagers": ("SERVE_REQ", "SERVE_RESP")},
    },
}

# ---------------------------------------------------------------------------
# request/response registry
# ---------------------------------------------------------------------------
# Every request-shaped constant and where its response comes back.
# ``loop`` names the registry.RECV_LOOPS entry whose dispatch span must
# dispatch the response constant (the protocol-order pass verifies it);
# ``loop: None`` requires a written reason (responses consumed outside
# any registered loop).
REQUESTS = {
    "GET_LOCATIONS": {"response": "REPLY", "loop": "worker.run"},
    "WAIT_OBJECTS": {"response": "REPLY", "loop": "worker.run"},
    "CREATE_ACTOR_REQ": {"response": "REPLY", "loop": "worker.run"},
    "GET_ACTOR": {"response": "REPLY", "loop": "worker.run"},
    "KILL_ACTOR": {"response": "REPLY", "loop": "worker.run"},
    "GCS_REQUEST": {"response": "REPLY", "loop": "worker.run"},
    "PULL_OBJECT": {"response": "REPLY", "loop": "worker.run"},
    "CHANNEL_REQ": {"response": "REPLY", "loop": "worker.run"},
    "DIRECT_RECONCILE": {"response": "REPLY", "loop": "worker.run"},
    "NODE_REQUEST": {"response": "NODE_REPLY", "loop": "daemon.run"},
    "START_WORKER": {"response": "NODE_REPLY", "loop": "head.daemon_serve"},
    "LOCALIZE_OBJECT": {"response": "NODE_REPLY",
                        "loop": "head.daemon_serve"},
    "REGISTER_NODE": {
        "response": "NODE_ACK", "loop": None,
        "reason": "the ACK is consumed synchronously by the "
                  "registration handshake (_connect_head) before the "
                  "daemon run loop starts; daemon.run carries a "
                  "matching NODE_ACK recv-loop exemption"},
    "SERVE_REQ": {"response": "SERVE_RESP", "loop": "serve.client"},
    "ACTOR_CALL": {"response": "ACTOR_RESULT", "loop": "worker.direct"},
    "PULL_DIRECT": {"response": "OBJ_EOF", "loop": "worker.direct"},
}

# ---------------------------------------------------------------------------
# payload schemas
# ---------------------------------------------------------------------------
# One entry per constant. ``variants`` is a tuple of alternative shapes
# (most constants have one); a send-site dict literal must match one
# variant: contain every ``required`` key, contain no key outside
# required|optional, and honor any declared compact-tuple ``arity``.
# ``optional`` also covers keys added conditionally via subscript
# stores after the literal. ``open: True`` marks payloads assembled
# dynamically (relay envelopes, result dicts built across functions) —
# key checking is skipped but the constant stays modeled.
#
# Request payloads list "req_id" optional everywhere: the request
# wrappers (Worker.request / DaemonHandle.request) inject it after the
# call-site literal, and responders read it back.
PAYLOADS = {
    # -- head -> worker ----------------------------------------------------
    "EXEC_TASK": {"variants": ({"required": ("spec",), "optional": ()},)},
    "EXEC_TASKS": {"variants": (
        {"required": ("specs_pickled",), "optional": ()},)},
    "CREATE_ACTOR": {"variants": ({"required": ("spec",), "optional": ()},)},
    "CANCEL_TASK": {"variants": (
        {"required": ("task_id",), "optional": ()},)},
    "RELEASE_OBJECTS": {"variants": (
        {"required": ("object_ids",), "optional": ()},)},
    "SHUTDOWN": {"variants": ({"required": (), "optional": ()},)},
    "REPLY": {"variants": (
        {"required": ("req_id", "result"), "optional": ()},)},
    "CHANNEL_OPEN": {"variants": ({"required": ("token",), "optional": ()},)},
    "RESULT_FWD": {"variants": ({"required": ("entries",), "optional": ()},)},
    "SEQ_SETTLED": {"variants": (
        {"required": ("caller_id", "seqs"), "optional": ("all",)},
        {"required": ("actor_id", "seqs"), "optional": ()},)},
    "TELEMETRY_DRAIN": {"variants": ({"required": (), "optional": ()},)},
    "RECALL_QUEUED": {"variants": ({"required": (), "optional": ()},)},
    # -- worker -> head ----------------------------------------------------
    "REF_COUNT": {"variants": (
        {"required": ("object_id", "delta"), "optional": ()},)},
    # Completion dicts are assembled across worker_proc execution paths
    # (results/error/nested/streamed/spec...) and pruned per route.
    "TASK_DONE": {"open": True},
    "TASKS_DONE": {"variants": ({"required": ("batch",), "optional": ()},)},
    "TASKS_RECALLED": {"variants": (
        {"required": ("task_ids",), "optional": ()},)},
    "GEN_ITEM": {"variants": (
        # channel path (DirectPlane.send_gen_item)
        {"required": ("t", "i", "loc", "nested"), "optional": ()},
        # head path (Worker._stream_generator)
        {"required": ("task_id", "index", "loc", "nested"),
         "optional": ()},)},
    "ACTOR_READY": {"variants": (
        {"required": ("actor_id", "error"), "optional": ()},)},
    "OWNED_PUT": {"variants": (
        {"required": ("object_id", "inline", "nested"), "optional": ()},
        {"required": ("object_id", "size", "nested"), "optional": ()},)},
    "GET_LOCATIONS": {"variants": (
        {"required": ("object_ids", "timeout"),
         "optional": ("req_id",)},)},
    "WAIT_OBJECTS": {"variants": (
        {"required": ("object_ids", "num_returns", "timeout"),
         "optional": ("req_id",)},)},
    "SUBMIT_TASK": {"variants": ({"required": ("spec",), "optional": ()},)},
    "SUBMIT_ACTOR_TASK": {"variants": (
        {"required": ("spec",), "optional": ()},)},
    "CREATE_ACTOR_REQ": {"variants": (
        {"required": ("spec",), "optional": ("req_id",)},)},
    "GET_ACTOR": {"variants": (
        {"required": ("name", "namespace"), "optional": ("req_id",)},)},
    "KILL_ACTOR": {"variants": (
        {"required": ("actor_id", "no_restart"),
         "optional": ("req_id",)},)},
    "GCS_REQUEST": {"variants": (
        {"required": ("op", "kwargs"), "optional": ("req_id",)},)},
    "PULL_OBJECT": {"variants": (
        {"required": ("object_id", "node"),
         "optional": ("materialize", "req_id")},)},
    "TASK_EVENTS": {"variants": (
        {"required": ("events", "dropped"),
         "optional": ("spans", "span_drops", "sub")},)},
    "METRICS_PUSH": {"variants": (
        {"required": ("worker_id", "node_id", "groups", "ts"),
         "optional": ()},)},
    "CHANNEL_REQ": {"variants": (
        {"required": ("actor_id",),
         "optional": ("req_id", "settled_below", "settled_set")},)},
    "CHANNEL_ADDR": {"variants": (
        {"required": ("token", "error"), "optional": ()},)},
    "DIRECT_DONE": {"variants": ({"required": ("entries",), "optional": ()},)},
    "DIRECT_RECONCILE": {"variants": (
        {"required": ("actor_id", "specs", "deltas", "req_id",
                      "callee_wid"),
         "optional": ("settled_below", "settled_set")},)},
    "REF_DELTAS": {"variants": ({"required": ("deltas",), "optional": ()},)},
    "WORKER_BLOCKED": {"variants": ({"required": (), "optional": ()},)},
    "WORKER_UNBLOCKED": {"variants": ({"required": (), "optional": ()},)},
    # -- direct channel ----------------------------------------------------
    "ACTOR_CALL": {"variants": (
        # compact fast path: one 11-slot tuple (task_id, actor, method,
        # name, return_ids, num_returns, fn_id, caller_id, caller_seq,
        # seq_preds, trace_ctx) — arity drift breaks _wire_spec
        {"required": ("c",), "optional": (), "arity": {"c": 11}},
        {"required": ("spec",), "optional": ()},)},
    "ACTOR_RESULT": {"variants": (
        {"required": ("t", "results", "error", "nested"),
         "optional": ("streamed",)},)},
    "GEN_CANCEL": {"variants": ({"required": ("t",), "optional": ()},)},
    "SERVE_REQ": {"variants": (
        {"required": ("r", "m", "b", "sn"), "optional": ("tr",)},)},
    "SERVE_RESP": {"variants": (
        {"required": ("r",), "optional": ("v", "e")},)},
    "SERVE_BODY_FREE": {"variants": ({"required": ("o",), "optional": ()},)},
    # -- direct object transfer --------------------------------------------
    "PULL_DIRECT": {"variants": (
        {"required": ("r", "o"), "optional": ()},)},
    # compact chunk tuple (rid, index, offset, total, oob-bytes) — the
    # bytes slot is a pickle-5 out-of-band view of the sealed segment,
    # never a pickled copy; arity drift breaks the chunk unpack.
    "OBJ_CHUNK": {"variants": (
        {"required": ("c",), "optional": (), "arity": {"c": 5}},)},
    "OBJ_EOF": {"variants": (
        {"required": ("r", "ok"), "optional": ("e",)},)},
    # -- head -> daemon ----------------------------------------------------
    "NODE_ACK": {"variants": (
        {"required": ("head_node_id_hex", "head_transfer_port"),
         "optional": ()},)},
    "NODE_SYNC": {"variants": (
        {"required": ("ts", "view"), "optional": ()},)},
    "START_WORKER": {"variants": (
        {"required": ("env_key", "dedicated", "nchips", "runtime_env"),
         "optional": ("req_id",)},)},
    "TO_WORKER": {"variants": (
        {"required": ("worker", "frame"), "optional": ()},)},
    "KILL_WORKER": {"variants": ({"required": ("worker",), "optional": ()},)},
    "WORKER_DEDICATED": {"variants": (
        {"required": ("worker", "actor_id"), "optional": ()},)},
    "SHUTDOWN_NODE": {"variants": ({"required": (), "optional": ()},)},
    "LOCALIZE_OBJECT": {"variants": (
        {"required": ("object_id", "node"), "optional": ("req_id",)},)},
    "DRAIN_NODE": {"variants": (
        {"required": ("node_id", "deadline_s"), "optional": ()},)},
    "NODE_REPLY": {"variants": (
        {"required": ("req_id", "result"), "optional": ()},)},
    # -- daemon -> head ----------------------------------------------------
    "REGISTER_NODE": {"variants": (
        {"required": ("node_id_hex", "resources", "transfer_port",
                      "hostname", "pid", "labels"), "optional": ()},)},
    "NODE_PING": {"variants": (
        {"required": ("ts", "store_used", "num_workers", "free_chips",
                      "pool_workers"),
         "optional": ("metrics", "metrics_ts")},)},
    "NODE_REQUEST": {"variants": (
        {"required": ("req_id", "op", "kwargs"), "optional": ()},)},
    "FROM_WORKER": {"variants": (
        {"required": ("worker", "frame"), "optional": ()},)},
    "WORKER_DIED": {"variants": ({"required": ("worker",), "optional": ()},)},
    "DRAIN_STATUS": {"variants": (
        {"required": ("node_id", "state", "ts"), "optional": ()},)},
}


def session_constants(session: dict) -> set:
    """Every constant any role of `session` may send."""
    out = set()
    for role in session["roles"].values():
        out.update(role["sends"])
    return out


def all_modeled_constants() -> set:
    out = set()
    for session in SESSIONS.values():
        out |= session_constants(session)
    return out


# ---------------------------------------------------------------------------
# runtime DFA interpreter (the wiretap's engine; also unit-testable
# without a cluster)
# ---------------------------------------------------------------------------
class SessionDFA:
    """Replays one connection's frame sequence against a SESSIONS entry.

    ``feed(direction, const_name, payload)`` returns the violations that
    frame produced (empty list == conforming). The interpreter checks
    sequencing invariants that hold regardless of which endpoint we are:
    plane membership, handshake-before-traffic, frame-after-teardown,
    request/response pairing, stream density/terminality, staged-body
    frees, and counter non-negativity. Per-state *send legality* is the
    static pass's job (it knows which states each send site is
    registered for); enforcing it here against the peer's inferred
    state would false-positive on legal races.

    ``extractors`` maps constant name -> callable(payload) -> dict with
    any of: ``key`` (pairing/stream key), ``index`` (stream index),
    ``streaming`` (opener starts a stream), ``stage`` (body oid this
    frame stages). Extractors never raise into the caller: a payload
    the extractor cannot read simply skips the keyed checks.
    """

    #: remembered terminated stream keys (item-after-terminal detection)
    TERMINATED_RING = 256

    def __init__(self, session_name: str, role: str, conn: str,
                 extractors: Optional[Dict[str, Callable]] = None):
        self.session_name = session_name
        self.session = SESSIONS[session_name]
        self.role = role
        self.conn = conn
        self.extractors = extractors or {}
        self.state = self.session.get("runtime_initial",
                                      self.session["initial"])
        self.consts = session_constants(self.session)
        self.recent: List[Tuple[str, str]] = []  # (direction, const) ring
        self.outstanding: Dict[Any, int] = {}    # pairing key -> count
        self.rids: set = set()                   # rid_resp outstanding
        self.streams: Dict[Any, int] = {}        # stream key -> next index
        self.cancelled: set = set()
        self.terminated: List[Any] = []
        self.staged_by_us: set = set()
        self.staged_by_peer: set = set()
        self.counters: Dict[str, int] = {}

    # -- plumbing ------------------------------------------------------
    def _extract(self, const: str, payload: Any) -> Dict[str, Any]:
        fn = self.extractors.get(const)
        if fn is None:
            return {}
        try:
            return fn(payload) or {}
        except Exception:
            return {}

    def _violation(self, kind: str, const: str, direction: str,
                   **detail: Any) -> Dict[str, Any]:
        v = {"kind": kind, "session": self.session_name,
             "conn": self.conn, "role": self.role, "state": self.state,
             "dir": direction, "const": const,
             "recent": list(self.recent)}
        v.update(detail)
        return v

    def note_request(self, rid: Any) -> None:
        """Register an outstanding rid-keyed request (fed from the
        request-wrapper chokepoint; the response constant must drain
        it)."""
        self.rids.add(rid)

    # -- the interpreter -----------------------------------------------
    def feed(self, direction: str, const: str,
             payload: Any) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        sess = self.session
        if const not in self.consts:
            out.append(self._violation("wrong-plane", const, direction))
            self._remember(direction, const)
            return out
        if self.state == "CLOSED":
            out.append(self._violation(
                "frame-after-teardown", const, direction,
                teardown=sess["teardown"]))
        handshake = sess["handshake"]
        if handshake and self.state == sess["initial"] \
                and const not in handshake:
            out.append(self._violation(
                "frame-before-handshake", const, direction,
                expected=handshake[0]))
        if handshake and const == handshake[0] \
                and self.state != sess["initial"] and self.state != "CLOSED":
            out.append(self._violation(
                "duplicate-handshake", const, direction))

        ext = self._extract(const, payload)

        # pairing -------------------------------------------------------
        for pair in sess["pairs"]:
            key = ext.get("key")
            if const == pair["req"] and key is not None:
                self.outstanding[key] = self.outstanding.get(key, 0) + 1
                if ext.get("streaming"):
                    self.streams[key] = 0
            elif const == pair["resp"] and key is not None:
                if self.outstanding.get(key, 0) <= 0:
                    out.append(self._violation(
                        "response-without-request", const, direction,
                        pair_req=pair["req"], key=repr(key)))
                else:
                    self.outstanding[key] -= 1
                    if not self.outstanding[key]:
                        del self.outstanding[key]

        # rid_resp (request-wrapper pairing) ----------------------------
        if sess.get("rid_resp") and const == sess["rid_resp"] \
                and direction == "recv":
            rid = ext.get("key")
            if rid is not None:
                if rid in self.rids:
                    self.rids.discard(rid)
                else:
                    out.append(self._violation(
                        "response-without-request", const, direction,
                        key=repr(rid)))

        # streams -------------------------------------------------------
        # One session may carry several stream kinds (generator items,
        # object-transfer chunks); a bare dict is the one-stream form.
        specs = sess["streams"]
        if isinstance(specs, dict):
            specs = (specs,)
        for streams in specs or ():
            key = ext.get("key")
            if const == streams["item"] and key is not None:
                idx = ext.get("index")
                if key in self.streams:
                    want = self.streams[key]
                    if idx is not None and idx != want:
                        out.append(self._violation(
                            "stream-gap", const, direction,
                            key=repr(key), expected=want, got=idx))
                        self.streams[key] = (idx + 1) if idx is not None \
                            else want
                    else:
                        self.streams[key] = want + 1
                elif key in self.cancelled:
                    pass  # post-cancel in-flight items drain legally
                elif key in self.terminated:
                    out.append(self._violation(
                        "item-after-terminal", const, direction,
                        key=repr(key)))
                else:
                    out.append(self._violation(
                        "stream-item-without-call", const, direction,
                        key=repr(key)))
            elif const == streams["terminal"]:
                if key is not None and (key in self.streams
                                        or key in self.cancelled):
                    self.streams.pop(key, None)
                    self.cancelled.discard(key)
                    self._terminate(key)
                elif key is not None and ext.get("streamed"):
                    self._terminate(key)
            elif streams["cancel"] is not None \
                    and const == streams["cancel"]:
                # Cancel of an unknown/finished stream is a legal race.
                if key is not None and key in self.streams:
                    del self.streams[key]
                    self.cancelled.add(key)

        # staged-body frees ---------------------------------------------
        frees = sess["frees"]
        if frees is not None:
            stage = ext.get("stage")
            if const in frees["stagers"] and stage is not None:
                (self.staged_by_us if direction == "send"
                 else self.staged_by_peer).add(stage)
            elif const == frees["free"]:
                oid = ext.get("key")
                pool = self.staged_by_peer if direction == "send" \
                    else self.staged_by_us
                if oid is not None:
                    if oid in pool:
                        pool.discard(oid)
                    else:
                        out.append(self._violation(
                            "free-without-stage", const, direction,
                            oid=repr(oid)))

        # counters ------------------------------------------------------
        for counter in sess["counters"]:
            if const == counter["up"]:
                self.counters[counter["up"]] = \
                    self.counters.get(counter["up"], 0) + 1
            elif const == counter["down"]:
                n = self.counters.get(counter["up"], 0) - 1
                self.counters[counter["up"]] = n
                if n < 0:
                    out.append(self._violation(
                        "unbalanced-counter", const, direction,
                        counter=counter["up"], count=n))

        # state advance / teardown --------------------------------------
        if const in sess["advance"] and self.state != "CLOSED":
            self.state = sess["advance"][const]
        if sess["teardown"] is not None and const == sess["teardown"]:
            self.state = "CLOSED"
        if const == "DIRECT_RECONCILE" and self.session_name == "direct":
            # Reconcile IS the drain: every outstanding call/stream is
            # settled by the head from the shipped residuals.
            self.outstanding.clear()
            self.streams.clear()
            self.cancelled.clear()

        self._remember(direction, const)
        return out

    def _remember(self, direction: str, const: str) -> None:
        self.recent.append((direction, const))
        if len(self.recent) > 8:
            del self.recent[0]

    def _terminate(self, key: Any) -> None:
        self.terminated.append(key)
        if len(self.terminated) > self.TERMINATED_RING:
            del self.terminated[0]
