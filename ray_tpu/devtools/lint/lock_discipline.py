"""lock-discipline pass.

Invariant: no blocking call sits lexically inside a ``with <lock>:``
suite for a designated hot-path lock (registry.HOT_LOCKS) — socket
send/recv, ``os.writev``/``os.read``, payload pickling, ``time.sleep``,
thread ``.join()``, ``Future.result()``, subprocess, file I/O. These
locks serialize recv loops, dispatch, and writer drains; a holder that
blocks on a peer wedges every other thread behind it (the exact shape
of the blocking-send-under-``_req_lock`` bug fixed in PR 2 review).

``Condition.wait`` is deliberately NOT a blocking call here: waiting on
the condition of the very lock you hold is the one legitimate blocking
operation under a lock (it releases while parked).

Escape hatch: ``# lint: blocking-under-lock-ok <reason>`` on the call
line or the ``with`` line — for sites where the block is bounded and
intentional (e.g. a bounded backpressure wait).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from . import registry
from .core import LintTree, SourceFile, Violation, walk

PASS = "lock-discipline"
RULE = "blocking-under-lock"

# Attribute names too generic to match on a non-self receiver (every
# other class has a `_lock`); self-receivers are class-scoped instead.
_GENERIC_ATTRS = {"_lock", "_cond"}

_PICKLERS = {"pickle", "cloudpickle", "serialization", "P"}


def _walk_no_defs(stmts: Iterable[ast.stmt]):
    """Walk statements without descending into nested function/lambda
    bodies (those run later, not under the lock)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _blocking_desc(node: ast.Call) -> Optional[str]:
    """A short description when `node` is a blocking call, else None."""
    fn = node.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "open()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    recv = fn.value.id if isinstance(fn.value, ast.Name) else None
    if attr == "sleep":
        return f"{recv or '<expr>'}.sleep()"
    if recv in ("os", "_os") and attr in registry.BLOCKING_OS_ATTRS:
        return f"os.{attr}()"
    if recv in registry.BLOCKING_MODULES:
        return f"{recv}.{attr}()"
    if attr == "join":
        # str.join takes exactly one iterable arg; a thread/process join
        # takes none or a numeric timeout — only flag the latter shapes.
        if not node.args and not node.keywords:
            return f"{recv or '<expr>'}.join()"
        if len(node.args) == 1 and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, (int, float)):
            return f"{recv or '<expr>'}.join(timeout)"
        return None
    if attr in ("dumps", "dump_message", "dump_messages",
                "dump_message_parts"):
        if recv in _PICKLERS:
            return f"{recv}.{attr}()"
        return None
    if attr in registry.BLOCKING_ATTRS:
        return f"{recv + '.' if recv else ''}{attr}()"
    return None


def _hot_lock_name(sf: SourceFile, item: ast.withitem,
                   class_attrs: Dict[str, Set[str]],
                   file_attrs: Set[str],
                   scope: str) -> Optional[str]:
    expr = item.context_expr
    if not (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)):
        return None
    recv, attr = expr.value.id, expr.attr
    cls = scope.split(".", 1)[0]
    if recv == "self":
        if attr in class_attrs.get(cls, ()):  # class-scoped designation
            return f"{cls}.{attr}"
        return None
    # Non-self receiver (e.g. `with handle.send_lock:` from the recv
    # mux): match by attr name alone, but only for names unique enough
    # to be unambiguous in this file.
    if attr in file_attrs and attr not in _GENERIC_ATTRS:
        return f"{recv}.{attr}"
    return None


def run(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    by_file: Dict[str, Dict[str, Set[str]]] = {}
    for (relpath, cls), attrs in registry.HOT_LOCKS.items():
        by_file.setdefault(relpath, {})[cls] = set(attrs)

    for relpath, class_attrs in sorted(by_file.items()):
        sf = tree.get(relpath)
        if sf is None:
            continue
        file_attrs: Set[str] = set().union(*class_attrs.values())
        for node in walk(sf.tree):
            if not isinstance(node, ast.With):
                continue
            scope = sf.scope_of(node)
            lock = None
            for item in node.items:
                lock = _hot_lock_name(sf, item, class_attrs, file_attrs,
                                      scope)
                if lock:
                    break
            if not lock:
                continue
            for inner in _walk_no_defs(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                desc = _blocking_desc(inner)
                if desc is None:
                    continue
                if sf.suppressed(RULE, inner.lineno, node.lineno):
                    continue
                out.append(Violation(
                    PASS, relpath, inner.lineno,
                    f"blocking call {desc} lexically inside "
                    f"`with {lock}:` — a stalled peer holds the hot "
                    f"lock against every other thread; move the call "
                    f"outside the critical section or annotate "
                    f"`# lint: {RULE}-ok <reason>`",
                    scope=scope, key=f"{lock}:{desc}"))
    return out
