"""Command-line entry: ``python -m ray_tpu.devtools.lint``.

Modes:
    (default)            run all passes, ratchet against baseline.json;
                         exit 1 on any NEW violation
    --no-baseline        full report of every violation, exit 1 if any
    --update-baseline    rewrite baseline.json from the current tree
    --root DIR           analyze a different tree (fixtures/tests); the
                         baseline defaults to empty then
    --since REV          incremental gate: passes still run on the FULL
                         tree (the cross-file checks need it), but only
                         violations in files changed since REV (plus
                         untracked files) are reported/failed — the
                         fast-CI shape. Stale-fingerprint burndown is
                         skipped (unchanged files are out of scope), and
                         --update-baseline refuses a narrowed run.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from . import PASS_NAMES
from .core import (LintTree, apply_baseline, fingerprint_counts,
                   load_baseline, run_passes, save_baseline)

_LINT_DIR = os.path.dirname(os.path.abspath(__file__))
DEFAULT_ROOT = os.path.dirname(os.path.dirname(_LINT_DIR))  # ray_tpu/
DEFAULT_BASELINE = os.path.join(_LINT_DIR, "baseline.json")


def changed_files(root: str, rev: str) -> Set[str]:
    """Lint-root-relative paths changed since `rev` (committed diffs,
    staged/unstaged edits, and untracked files). Raises
    ``subprocess.CalledProcessError`` on an unknown rev and
    ``FileNotFoundError`` when git is absent."""
    top = subprocess.run(
        ["git", "-C", root, "rev-parse", "--show-toplevel"],
        check=True, capture_output=True, text=True).stdout.strip()
    out: Set[str] = set()
    for cmd in (["git", "-C", root, "diff", "--name-only", rev, "--"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard"]):
        res = subprocess.run(cmd, check=True, capture_output=True,
                             text=True)
        for line in res.stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            # git paths are repo-root-relative; violations are
            # lint-root-relative.
            rel = os.path.relpath(os.path.join(top, line), root)
            if not rel.startswith(".."):
                out.add(rel)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="raylint: project-invariant static analysis "
                    "(docs/STATIC_ANALYSIS.md)")
    parser.add_argument("--root", default=None,
                        help="package directory to analyze "
                             "(default: the installed ray_tpu package)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: the checked-in "
                             "devtools/lint/baseline.json; empty when "
                             "--root points elsewhere)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report everything")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the current tree")
    parser.add_argument("--passes", nargs="*", choices=PASS_NAMES,
                        default=None, metavar="PASS",
                        help="subset of passes to run")
    parser.add_argument("--since", default=None, metavar="REV",
                        help="report only violations in files changed "
                             "since REV (full-tree analysis, narrowed "
                             "reporting — the incremental CI gate)")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text", dest="fmt",
                        help="output format: human text (default), a "
                             "machine-readable JSON report, or GitHub "
                             "workflow ::error annotation lines")
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else DEFAULT_ROOT
    if not os.path.isdir(root):
        print(f"raylint: no such directory: {root}", file=sys.stderr)
        return 2
    baseline_path = args.baseline
    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE if args.root is None else None

    tree = LintTree(root)
    timings = {}
    violations = run_passes(tree, args.passes, timings=timings)
    per_pass = {}
    for v in violations:
        per_pass[v.pass_name] = per_pass.get(v.pass_name, 0) + 1

    if args.update_baseline:
        # The checked-in baseline must only ever be rewritten from a
        # FULL run of the real tree: a narrowed run (--passes) or a
        # foreign tree (--root) would silently clobber it — deleting
        # the live fingerprints (every baselined violation turns NEW)
        # or masking real ones behind fixture fingerprints.
        if args.passes is not None:
            print("raylint: refusing --update-baseline with --passes "
                  "(a partial run would drop the other passes' "
                  "baselined fingerprints)", file=sys.stderr)
            return 2
        if args.root is not None and args.baseline is None:
            print("raylint: --update-baseline with --root requires an "
                  "explicit --baseline path (refusing to overwrite the "
                  "checked-in baseline with another tree's results)",
                  file=sys.stderr)
            return 2
        if args.since is not None:
            print("raylint: refusing --update-baseline with --since "
                  "(the ratchet must be rewritten from a full run, "
                  "never a changed-files slice)", file=sys.stderr)
            return 2
        path = baseline_path or DEFAULT_BASELINE
        save_baseline(path, violations)
        print(f"raylint: baseline updated: {path} "
              f"({len(violations)} violations, "
              f"{len(fingerprint_counts(violations))} fingerprints)")
        return 0

    baseline = {}
    if baseline_path and not args.no_baseline:
        baseline = load_baseline(baseline_path)
    res = apply_baseline(violations, baseline)

    if args.since is not None:
        try:
            scope = changed_files(root, args.since)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            print(f"raylint: --since {args.since}: cannot resolve "
                  f"changed files: {detail.strip()}", file=sys.stderr)
            return 2
        res.new = [v for v in res.new if v.file in scope]
        # Unchanged files are out of scope: a fingerprint that stopped
        # firing there is the FULL run's burndown signal, not this one's.
        res.fixed = []
        if not args.quiet and args.fmt == "text":
            print(f"raylint: --since {args.since}: narrowed to "
                  f"{len(scope)} changed file(s)")

    if args.fmt == "json":
        new_set = {id(v) for v in res.new}
        report = {
            "total": len(violations),
            "new": len(res.new),
            "baselined": len(violations) - len(res.new),
            "per_pass": {k: per_pass.get(k, 0) for k in PASS_NAMES},
            "per_pass_ms": {k: round(timings[k], 3)
                            for k in PASS_NAMES if k in timings},
            "stale_fingerprints": sorted(res.fixed),
            "violations": [
                {"file": v.file, "line": v.line, "pass": v.pass_name,
                 "message": v.message, "scope": v.scope,
                 "fingerprint": v.fingerprint, "new": id(v) in new_set}
                for v in violations],
        }
        print(json.dumps(report, indent=1))
        return 1 if res.new else 0

    if args.fmt == "github":
        # Workflow-annotation lines: one ::error per NEW violation so
        # the PR diff view pins each regression to its source line.
        for v in res.new:
            print(f"::error file={v.file},line={v.line},"
                  f"title=raylint {v.pass_name}::{v.message}")
        for fp in sorted(res.fixed):
            print(f"::notice title=raylint stale baseline::{fp} no "
                  f"longer fires; refresh with --update-baseline")
        return 1 if res.new else 0

    if not args.quiet:
        for v in res.new:
            print(v.render())
        if res.fixed:
            print(f"raylint: {len(res.fixed)} baselined fingerprint(s) "
                  f"no longer fire — burn them down with "
                  f"--update-baseline:")
            for fp in sorted(res.fixed):
                print(f"  stale: {fp}")
        summary = ", ".join(f"{k}={per_pass.get(k, 0)}"
                            for k in PASS_NAMES)
        print(f"raylint: {len(violations)} total ({summary}); "
              f"{len(violations) - len(res.new)} baselined, "
              f"{len(res.new)} new")
    return 1 if res.new else 0


if __name__ == "__main__":
    sys.exit(main())
