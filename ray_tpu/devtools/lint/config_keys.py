"""config-keys pass.

Invariant: every config key read anywhere in the package has a declared
default in ``_private/config.py`` (``RayConfig._DEFAULTS``). RayConfig
raises AttributeError on unknown attributes at runtime — but only when
the typo'd line actually executes, which for rarely-taken branches
(reconnect paths, spill escalation) can be never-in-CI. This pass makes
the check static: ``ray_config.<key>``, ``ray_config.set("<key>", ..)``
and ``getattr(ray_config, "<key>")`` all resolve against the declared
defaults at lint time.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import LintTree, SourceFile, Violation, walk

PASS = "config-keys"
CONFIG_FILE = "_private/config.py"

_METHODS = {"set", "snapshot"}


def parse_default_keys(sf: SourceFile) -> Set[str]:
    for node in walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "RayConfig":
            for stmt in node.body:
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                    value = stmt.value
                elif isinstance(stmt, ast.AnnAssign):
                    targets = [stmt.target]
                    value = stmt.value
                else:
                    continue
                if any(isinstance(t, ast.Name) and t.id == "_DEFAULTS"
                       for t in targets) and isinstance(value, ast.Dict):
                    return {k.value for k in value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
    return set()


def run(tree: LintTree) -> List[Violation]:
    cfg = tree.get(CONFIG_FILE)
    if cfg is None:
        return []
    keys = parse_default_keys(cfg)
    out: List[Violation] = []

    def unknown(sf: SourceFile, node: ast.AST, key: str) -> None:
        out.append(Violation(
            PASS, sf.relpath, node.lineno,
            f"config key {key!r} has no declared default in "
            f"config.py _DEFAULTS — a typo here silently never "
            f"matches an env override (and raises only when this "
            f"branch finally executes)",
            scope=sf.scope_of(node), key=f"unknown-key:{key}"))

    for sf in tree.iter_files():
        if sf.relpath == CONFIG_FILE:
            continue
        for node in walk(sf.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "ray_config":
                attr = node.attr
                if attr.startswith("_") or attr in _METHODS:
                    # .set("<key>", ...) checks its literal argument
                    parent = getattr(node, "_lint_parent", None)
                    if attr == "set" and isinstance(parent, ast.Call) \
                            and parent.func is node and parent.args \
                            and isinstance(parent.args[0], ast.Constant) \
                            and isinstance(parent.args[0].value, str) \
                            and parent.args[0].value not in keys:
                        unknown(sf, parent, parent.args[0].value)
                    continue
                if attr not in keys:
                    unknown(sf, node, attr)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "getattr" \
                    and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id == "ray_config" \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str) \
                    and node.args[1].value not in keys:
                unknown(sf, node, node.args[1].value)
    return out
