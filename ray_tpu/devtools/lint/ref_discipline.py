"""ref-discipline: ownership/refcount conservation as a static pass.

The direct-call plane re-derives the reference's core-worker invariant
— no object freed while any node holds a live reference — from
buffered accounting (``REF_DELTAS`` / ``DIRECT_DONE`` residual
transfers drained at ``flush_accounting`` barriers). PR 5 burned eight
review rounds on exactly this surface; this pass pins the four
properties those rounds converged on (registries in registry.py):

  unregistered-mutation-helper / stale-mutation-helper
      Every def named like a refcount mutator inside REF_FILES is
      declared in REF_MUTATION_HELPERS (a new helper is a new
      conservation obligation), and the registry carries no rot.

  unpaired-park
      A function that parks accounting (writes into ``_ref_buf`` /
      ``_done_buf`` / ``_refs``) is lexically paired with a drain
      barrier, is the barrier, or names its deferred barrier in
      REF_PARK_DEFERRED (escape hatch: ``# lint: ref-park-ok``).

  unguarded-elision
      A ``continue``-only guard inside a barrier function (the entry
      elision) must reference escape-marked state — directly or via a
      local derived from it — so an entry the head is already waiting
      on can never be silently dropped (the PR 5 elision bug).

  orphan-field / phantom-field / missing-producer / missing-consumer /
  stale-exempt
      Residual-transfer payload conservation: every field written into
      a DIRECT_DONE / REF_DELTAS / GEN_ITEM payload is read by the
      registered head-side (or caller-side) consumer, and vice versa.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import registry
from .core import LintTree, SourceFile, Violation, walk

PASS = "ref-discipline"
PARK_RULE = "ref-park"
ELISION_RULE = "ref-elision"
FIELD_RULE = "ref-field"
RESERVE_RULE = "reserve-seal"


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------
def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.<attr>` (or any single-name receiver) -> attr name."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return node.attr
    return None


def _call_names(func: ast.AST) -> Iterable[str]:
    """Terminal names a call expression could resolve through."""
    if isinstance(func, ast.Name):
        yield func.id
    elif isinstance(func, ast.Attribute):
        yield func.attr


def _function_calls(fn: ast.AST, names: Set[str]) -> List[ast.Call]:
    out = []
    for node in walk(fn):
        if isinstance(node, ast.Call):
            for n in _call_names(node.func):
                if n in names:
                    out.append(node)
                    break
    return out


def _p_const(node: ast.AST) -> Optional[str]:
    """`P.<CONST>` (or bare `<CONST>` uppercase name) -> constant name."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "P":
        return node.attr
    if isinstance(node, ast.Name) and node.id.isupper():
        return node.id
    return None


def _dict_str_keys(node: ast.Dict) -> List[Tuple[str, int]]:
    out = []
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            out.append((k.value, k.lineno))
    return out


# ---------------------------------------------------------------------------
# check 1: mutation-helper inventory
# ---------------------------------------------------------------------------
def check_mutation_inventory(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    found: Set[Tuple[str, str]] = set()
    for rel in registry.REF_FILES:
        sf = tree.get(rel)
        if sf is None:
            continue
        for node in walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in registry.REF_MUTATION_METHOD_NAMES:
                qual = sf.scope_of(node)
                found.add((rel, qual))
                if (rel, qual) in registry.REF_MUTATION_HELPERS:
                    continue
                if sf.suppressed(PARK_RULE, node.lineno):
                    continue
                out.append(Violation(
                    PASS, rel, node.lineno,
                    f"refcount-mutation helper {qual} is not declared in "
                    f"registry.REF_MUTATION_HELPERS — a new mutation "
                    f"helper is a new conservation obligation; register "
                    f"it (and its journal hook under refdebug)",
                    scope=qual, key=f"unregistered-mutation-helper:{qual}"))
    for rel, qual in sorted(registry.REF_MUTATION_HELPERS):
        if tree.get(rel) is None:
            continue
        if (rel, qual) not in found:
            out.append(Violation(
                PASS, rel, 1,
                f"registry.REF_MUTATION_HELPERS names {qual} which no "
                f"longer exists in {rel} (registry rot)",
                scope="<module>", key=f"stale-mutation-helper:{qual}"))
    return out


# ---------------------------------------------------------------------------
# check 2: park sites lexically paired with a drain barrier
# ---------------------------------------------------------------------------
def _park_sites(sf: SourceFile, fn: ast.AST) -> List[Tuple[str, int]]:
    """(attr, line) for every accounting-park write inside `fn`:
    subscript stores / augmented subscript stores on a park attr, and
    ``.append(...)`` calls on one. Whole-attr reassignment (the drain)
    and reads/pops are NOT parks."""
    sites: List[Tuple[str, int]] = []
    for node in walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr in registry.REF_PARK_ATTRS:
                        sites.append((attr, node.lineno))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append":
            attr = _self_attr(node.func.value)
            if attr in registry.REF_PARK_ATTRS:
                sites.append((attr, node.lineno))
    return sites


def check_park_pairing(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    for rel in registry.REF_PARK_FILES:
        sf = tree.get(rel)
        if sf is None:
            continue
        for node in walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = sf.scope_of(node)
            if node.name in registry.REF_BARRIER_FUNCS:
                continue  # the barrier's own buffer handling
            sites = _park_sites(sf, node)
            if not sites:
                continue
            if _function_calls(node, set(registry.REF_BARRIER_FUNCS)):
                continue  # lexically paired
            if (rel, qual) in registry.REF_PARK_DEFERRED:
                continue  # reasoned deferral
            for attr, line in sites:
                if sf.suppressed(PARK_RULE, line):
                    continue
                out.append(Violation(
                    PASS, rel, line,
                    f"accounting parked into self.{attr} with no drain "
                    f"barrier in {qual} — call flush_accounting / "
                    f"_flush_accounting_locked, add a reasoned "
                    f"registry.REF_PARK_DEFERRED entry, or annotate "
                    f"`# lint: {PARK_RULE}-ok <reason>` (an idle worker "
                    f"has no later barrier: parked deltas strand head-"
                    f"side waiters — the PR 5 hang shape)",
                    scope=qual, key=f"unpaired-park:{attr}"))
    return out


# ---------------------------------------------------------------------------
# check 3: elision guards reference escape-marked state
# ---------------------------------------------------------------------------
def _escape_tainted_names(fn: ast.AST) -> Set[str]:
    """Local names assigned from an expression that reads escape state."""
    tainted: Set[str] = set()
    for node in walk(fn):
        if isinstance(node, ast.Assign):
            reads_escape = any(
                _self_attr(sub) in registry.REF_ESCAPE_STATE
                for sub in walk(node.value))
            if reads_escape:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
    return tainted


def _references_escape_state(test: ast.AST, tainted: Set[str]) -> bool:
    for sub in walk(test):
        if _self_attr(sub) in registry.REF_ESCAPE_STATE:
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
    return False


def check_elision_guards(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    for rel, qual in sorted(registry.REF_ELISION_FUNCS):
        sf = tree.get(rel)
        if sf is None:
            continue
        fns = sf.functions([qual])
        if not fns:
            out.append(Violation(
                PASS, rel, 1,
                f"registry.REF_ELISION_FUNCS names {qual} which no "
                f"longer exists in {rel} (registry rot)",
                scope="<module>", key=f"stale-elision-func:{qual}"))
            continue
        for fn in fns:
            tainted = _escape_tainted_names(fn)
            for node in walk(fn):
                if not isinstance(node, ast.If):
                    continue
                if len(node.body) != 1 \
                        or not isinstance(node.body[0], ast.Continue):
                    continue
                if _references_escape_state(node.test, tainted):
                    continue
                if sf.suppressed(ELISION_RULE, node.lineno):
                    continue
                out.append(Violation(
                    PASS, rel, node.lineno,
                    f"accounting-entry elision in {qual} does not "
                    f"consult escape-marked state "
                    f"({', '.join(sorted(registry.REF_ESCAPE_STATE))}) "
                    f"— an escaped id netting zero residual would be "
                    f"silently dropped while the head holds a waiter "
                    f"on it (the PR 5 elision bug)",
                    scope=qual, key="unguarded-elision"))
    return out


# ---------------------------------------------------------------------------
# check 4: residual-transfer payload field conservation
# ---------------------------------------------------------------------------
def _produced_fields(sf: SourceFile, fn: ast.AST, entry_vars: Set[str],
                     send_const: str) -> Dict[str, int]:
    """field name -> first producing line inside one producer fn."""
    fields: Dict[str, int] = {}

    def note(key: str, line: int) -> None:
        fields.setdefault(key, line)

    for node in walk(fn):
        # {'k': ...} literal bound to an entry var
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in entry_vars:
                    for k, line in _dict_str_keys(node.value):
                        note(k, line)
        # entry_var['k'] = ...
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in entry_vars \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    note(t.slice.value, node.lineno)
        # send(P.CONST, {...}) with the payload dict inline
        if isinstance(node, ast.Call) and node.args:
            if _p_const(node.args[0]) == send_const:
                for arg in node.args[1:]:
                    if isinstance(arg, ast.Dict):
                        for k, line in _dict_str_keys(arg):
                            note(k, line)
    return fields


def _consumed_fields(fn: ast.AST, payload_vars: Set[str]) -> Set[str]:
    """String keys read off the payload vars: var['k'] loads and
    var.get('k', ...) calls."""
    keys: Set[str] = set()
    for node in walk(fn):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in payload_vars \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            keys.add(node.slice.value)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in payload_vars \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            keys.add(node.args[0].value)
    return keys


def check_payload_conservation(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    for payload_name, spec in sorted(registry.REF_PAYLOADS.items()):
        psf = tree.get(spec["producer_file"])
        csf = tree.get(spec["consumer_file"])
        if psf is None or csf is None:
            continue  # fixture subset: payload not in scope
        entry_vars = set(spec.get("entry_vars") or ())
        payload_vars = set(spec.get("payload_vars") or ())
        exempt = spec.get("exempt") or {}

        produced: Dict[str, Tuple[int, str]] = {}  # key -> (line, scope)
        for qual in spec["producers"]:
            fns = psf.functions([qual])
            if not fns:
                out.append(Violation(
                    PASS, spec["producer_file"], 1,
                    f"registry.REF_PAYLOADS[{payload_name!r}] names "
                    f"producer {qual} which does not exist (registry "
                    f"rot)", scope="<module>",
                    key=f"missing-producer:{payload_name}:{qual}"))
                continue
            for fn in fns:
                for k, line in _produced_fields(
                        psf, fn, entry_vars, spec["send_const"]).items():
                    produced.setdefault(k, (line, psf.scope_of(fn)))

        consumed: Set[str] = set()
        for qual in spec["consumers"]:
            fns = csf.functions([qual])
            if not fns:
                out.append(Violation(
                    PASS, spec["consumer_file"], 1,
                    f"registry.REF_PAYLOADS[{payload_name!r}] names "
                    f"consumer {qual} which does not exist (registry "
                    f"rot)", scope="<module>",
                    key=f"missing-consumer:{payload_name}:{qual}"))
                continue
            for fn in fns:
                consumed |= _consumed_fields(fn, payload_vars)

        for k, (line, scope) in sorted(produced.items()):
            if k in consumed or k in exempt:
                continue
            if psf.suppressed(FIELD_RULE, line):
                continue
            out.append(Violation(
                PASS, spec["producer_file"], line,
                f"field {k!r} written into the {payload_name} payload "
                f"is never read by its consumer "
                f"({', '.join(spec['consumers'])}) — orphaned residual-"
                f"transfer fields rot into silent accounting loss; "
                f"consume it, delete it, or exempt it with a reason in "
                f"registry.REF_PAYLOADS",
                scope=scope, key=f"orphan-field:{payload_name}:{k}"))
        for k in sorted(consumed - set(produced) - set(exempt)):
            out.append(Violation(
                PASS, spec["consumer_file"], 1,
                f"consumer of {payload_name} reads field {k!r} which no "
                f"registered producer writes — a phantom read masks "
                f"producer regressions", scope=spec["consumers"][0],
                key=f"phantom-field:{payload_name}:{k}"))
        for k, reason in sorted(exempt.items()):
            if k in produced and k not in consumed:
                continue
            out.append(Violation(
                PASS, spec["producer_file"], 1,
                f"stale exemption for {payload_name} field {k!r} "
                f"(reason: {reason}): the field is "
                f"{'now consumed' if k in consumed else 'never produced'}"
                f" — drop the registry entry",
                scope="<module>", key=f"stale-exempt:{payload_name}:{k}"))
    return out


# ---------------------------------------------------------------------------
# check 5: reservations lexically paired with a settle (seal/abort)
# ---------------------------------------------------------------------------
def check_reserve_pairing(tree: LintTree) -> List[Violation]:
    """Every function that opens a store reservation
    (``reserve``/``_reserve`` call) must lexically settle it — a
    ``seal``/``abort``/``_abort_reserve`` call on every path is the
    contract, and a lexical settle is the statically checkable proxy
    (the same shape check_park_pairing uses for drain barriers).
    Streamed protocols that settle on a later message declare the
    terminal in registry.RESERVE_DEFERRED."""
    out: List[Violation] = []
    deferred_seen: Set[Tuple[str, str]] = set()
    for rel in registry.RESERVE_FILES:
        sf = tree.get(rel)
        if sf is None:
            continue
        for node in walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in registry.RESERVE_CALL_NAMES \
                    or node.name in registry.RESERVE_SETTLE_NAMES:
                continue  # the implementations themselves
            calls = _function_calls(node, set(registry.RESERVE_CALL_NAMES))
            if not calls:
                continue
            qual = sf.scope_of(node)
            if (rel, qual) in registry.RESERVE_DEFERRED:
                deferred_seen.add((rel, qual))
                continue
            if _function_calls(node, set(registry.RESERVE_SETTLE_NAMES)):
                continue  # lexically paired
            for call in calls:
                if sf.suppressed(RESERVE_RULE, call.lineno):
                    continue
                out.append(Violation(
                    PASS, rel, call.lineno,
                    f"reservation opened in {qual} with no lexical "
                    f"seal/abort — an unsettled reservation is charged-"
                    f"but-unreadable capacity and a truncation hazard "
                    f"for readers; settle it, add a reasoned "
                    f"registry.RESERVE_DEFERRED entry, or annotate "
                    f"`# lint: {RESERVE_RULE}-ok <reason>`",
                    scope=qual, key=f"unsettled-reserve:{qual}"))
    for rel, qual in sorted(registry.RESERVE_DEFERRED):
        if tree.get(rel) is None:
            continue
        if (rel, qual) not in deferred_seen:
            out.append(Violation(
                PASS, rel, 1,
                f"registry.RESERVE_DEFERRED names {qual} which no longer "
                f"opens a reservation in {rel} (registry rot)",
                scope="<module>", key=f"stale-reserve-deferred:{qual}"))
    return out


def run(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    out.extend(check_mutation_inventory(tree))
    out.extend(check_park_pairing(tree))
    out.extend(check_elision_guards(tree))
    out.extend(check_payload_conservation(tree))
    out.extend(check_reserve_pairing(tree))
    return out
