"""barrier-coverage: every head-bound send is ordered after the
accounting barrier.

The PR 5 round-7/8 hang shape: a worker ships a message the head acts
on (a submission, a put, a pull) while refcount residuals for the ids
it references sit parked in the direct plane's buffers — the head
frees or blocks on an object whose deltas are still in flight. The
repo's discipline is that every head-bound send chokepoint either
calls ``flush_accounting`` first (lexically, in the same function,
before the send) or is a message class that provably references no
buffered accounting state, recorded with a reason in
``registry.BARRIER_EXEMPT``.

Discovered sites: ``*.send(P.CONST, ...)`` / ``*.send_lazy(P.CONST,
...)`` calls in ``registry.BARRIER_SEND_FILES``. Sends routed through
a verified wrapper (``registry.BARRIER_WRAPPERS`` — e.g.
``Worker.request``, which flushes before every request) are covered by
construction; the pass instead verifies each wrapper still flushes
before its first send. Escape hatch for a single site:
``# lint: barrier-ok <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import registry
from .core import LintTree, Violation, walk

PASS = "barrier-coverage"
RULE = "barrier"


def _p_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "P":
        return node.attr
    if isinstance(node, ast.Name) and node.id.isupper():
        return node.id
    return None


def _barrier_lines(fn: ast.AST) -> List[int]:
    out = []
    for node in walk(fn):
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if name in registry.REF_BARRIER_FUNCS:
                out.append(node.lineno)
    return out


def run(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    sent_consts: Set[str] = set()

    for rel in registry.BARRIER_SEND_FILES:
        sf = tree.get(rel)
        if sf is None:
            continue
        for node in walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = sf.scope_of(node)
            in_barrier = node.name in registry.REF_BARRIER_FUNCS
            barriers = _barrier_lines(node)
            for sub in walk(node):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in registry.BARRIER_SEND_ATTRS
                        and sub.args):
                    continue
                const = _p_const(sub.args[0])
                if const is None:
                    continue
                sent_consts.add(const)
                if in_barrier:
                    continue  # the barrier's own drain sends
                if const in registry.BARRIER_EXEMPT:
                    continue
                if any(ln < sub.lineno for ln in barriers):
                    continue
                if sf.suppressed(RULE, sub.lineno):
                    continue
                out.append(Violation(
                    PASS, rel, sub.lineno,
                    f"head-bound send of P.{const} in {qual} is not "
                    f"preceded by an accounting barrier "
                    f"({'/'.join(sorted(registry.REF_BARRIER_FUNCS))}) — "
                    f"the head can act on ids whose refcount residuals "
                    f"are still parked here (the PR 5 hang shape); "
                    f"flush first, route through a verified wrapper, or "
                    f"add a reasoned registry.BARRIER_EXEMPT entry",
                    scope=qual, key=f"unflushed-send:{const}"))

    # Verified wrappers must actually flush before their first send.
    for rel, qual in sorted(registry.BARRIER_WRAPPERS):
        sf = tree.get(rel)
        if sf is None:
            continue
        fns = sf.functions([qual])
        if not fns:
            out.append(Violation(
                PASS, rel, 1,
                f"registry.BARRIER_WRAPPERS names {qual} which no longer "
                f"exists in {rel} (registry rot)",
                scope="<module>", key=f"stale-wrapper:{qual}"))
            continue
        for fn in fns:
            barriers = _barrier_lines(fn)
            first_send = None
            for sub in walk(fn):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in (registry.BARRIER_SEND_ATTRS
                                              | registry.BARRIER_WRAPPER_ATTRS):
                    if first_send is None or sub.lineno < first_send:
                        first_send = sub.lineno
            if first_send is None:
                continue
            if not any(ln < first_send for ln in barriers):
                out.append(Violation(
                    PASS, rel, first_send,
                    f"verified barrier wrapper {qual} no longer calls "
                    f"the accounting barrier before its first send — "
                    f"every site routed through it just lost coverage",
                    scope=qual, key=f"unflushed-wrapper:{qual}"))

    # Exemption hygiene: an exempted constant that is never sent from a
    # discovered chokepoint is registry rot (only when the real files
    # are in the analyzed tree — fixture subsets skip this).
    if all(tree.get(rel) is not None
           for rel in registry.BARRIER_SEND_FILES):
        for const in sorted(set(registry.BARRIER_EXEMPT) - sent_consts):
            out.append(Violation(
                PASS, registry.BARRIER_SEND_FILES[0], 1,
                f"registry.BARRIER_EXEMPT entry {const!r} matches no "
                f"discovered send chokepoint (registry rot)",
                scope="<module>", key=f"stale-exempt:{const}"))
    return out
