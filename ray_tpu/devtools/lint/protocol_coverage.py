"""protocol-coverage pass.

Invariant: every message constant defined in ``_private/protocol.py``
is dispatched by each recv loop that serves its plane (worker run loop,
daemon run loop, head daemon-serve, both worker-plane recv muxes), and
every dispatch chain's fallthrough HANDLES unknown types (log, counter,
error reply, or relay) instead of silently dropping the frame — the
exact bug class of the coalesced-frame drop fixed in review last PR.

Planes are parsed from protocol.py itself: section headers
(``# Message types: driver -> worker`` ...) give a default, and a
per-constant inline direction comment (``# head -> daemon: ...``)
overrides it — so a new constant is classified where it is declared,
and a constant the parser cannot classify is itself a violation.
The loop registry lives in registry.RECV_LOOPS.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import registry
from .core import LintTree, SourceFile, Violation, walk

PASS = "protocol-coverage"

PROTOCOL_FILE = "_private/protocol.py"

_SECTION_RE = re.compile(r"^#\s*Message types:\s*(?P<rest>.*)")
_SEPARATOR_RE = re.compile(r"^#\s*-{10,}")

_DIRECTIONS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("direct", (r"worker\s*<->\s*worker",)),
    ("to_worker", (r"(driver|owner|head|daemon)\s*->\s*worker",)),
    ("from_worker", (r"worker\s*->\s*(driver|owner|head|daemon)",)),
    ("head_to_daemon", (r"head\s*->\s*daemon",)),
    ("daemon_to_head", (r"daemon\s*->\s*head",)),
)
_EITHER_RE = re.compile(r"either\s+direction")


def _direction_of(text: str) -> List[str]:
    out = [plane for plane, pats in _DIRECTIONS
           if any(re.search(p, text) for p in pats)]
    return out


def _section_default(header_rest: str) -> Optional[str]:
    d = _direction_of(header_rest)
    if len(d) == 1:
        return d[0]
    return None  # e.g. "per-host daemon <-> head": per-constant comments


def parse_planes(sf: SourceFile) -> Tuple[Dict[str, Set[str]],
                                          List[Violation]]:
    """Classify every message constant into plane sets. Returns
    ({plane: {CONST, ...}}, violations) — a constant inside a message
    section that cannot be classified is a violation."""
    planes: Dict[str, Set[str]] = {
        "to_worker": set(), "from_worker": set(),
        "head_to_daemon": set(), "daemon_to_head": set(),
        "direct": set()}
    violations: List[Violation] = []

    # line -> section default plane ("" = inside a message section with
    # no single default; absent = outside any message section)
    section_at: Dict[int, str] = {}
    current: Optional[str] = None
    prev_blank = True
    for i, line in enumerate(sf.lines, start=1):
        stripped = line.strip()
        m = _SECTION_RE.match(stripped)
        if m:
            current = _section_default(m.group("rest")) or ""
        elif _SEPARATOR_RE.match(stripped):
            current = None
        elif prev_blank and line.startswith("#"):
            # A fresh column-0 comment paragraph (e.g. "# Object
            # location kinds") ends the message section; continuation
            # lines of a section header follow it WITHOUT a blank line,
            # so multi-line headers survive.
            current = None
        if current is not None:
            section_at[i] = current
        prev_blank = stripped == ""

    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        name = node.targets[0].id
        if name != name.upper() or node.lineno not in section_at:
            continue
        # Inline comment on the declaration line decides; the section
        # header is the fallback.
        comment = ""
        line = sf.lines[node.lineno - 1]
        if "#" in line:
            comment = line.split("#", 1)[1]
        if _EITHER_RE.search(comment):
            planes["head_to_daemon"].add(name)
            planes["daemon_to_head"].add(name)
            continue
        d = _direction_of(comment)
        if len(d) == 1:
            planes[d[0]].add(name)
            continue
        default = section_at[node.lineno]
        if default:
            planes[default].add(name)
        else:
            violations.append(Violation(
                PASS, sf.relpath, node.lineno,
                f"message constant {name} has no parseable direction "
                f"comment (e.g. '# head -> daemon: ...'); recv-loop "
                f"coverage cannot be checked for it",
                scope=sf.scope_of(node), key=f"undirected:{name}"))
    return planes, violations


# ---------------------------------------------------------------------------
# dispatch extraction
# ---------------------------------------------------------------------------
def _const_names(node: ast.AST) -> List[str]:
    """Protocol-constant names referenced by a comparator expression:
    ``P.EXEC_TASK`` / bare ``EXEC_TASK`` / tuples of either."""
    out: List[str] = []
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List, ast.Set)) \
        else [node]
    for e in elts:
        if isinstance(e, ast.Attribute) and e.attr == e.attr.upper() \
                and isinstance(e.value, ast.Name):
            out.append(e.attr)
        elif isinstance(e, ast.Name) and e.id == e.id.upper():
            out.append(e.id)
    return out


def _tests_dispatch_var(test: ast.AST, dispatch_vars: Set[str]) -> bool:
    for cmp_node in walk(test):
        if isinstance(cmp_node, ast.Compare) \
                and isinstance(cmp_node.left, ast.Name) \
                and cmp_node.left.id in dispatch_vars:
            return True
    return False


def dispatched_constants(sf: SourceFile, functions, dispatch_vars
                         ) -> Set[str]:
    found: Set[str] = set()
    dv = set(dispatch_vars)
    for fn in sf.functions(functions):
        for node in walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            if isinstance(node.left, ast.Name) and node.left.id in dv:
                for comparator in node.comparators:
                    found.update(_const_names(comparator))
            elif any(isinstance(c, ast.Name) and c.id in dv
                     for c in node.comparators):
                found.update(_const_names(node.left))
    return found


# ---------------------------------------------------------------------------
# fallthrough analysis
# ---------------------------------------------------------------------------
def _chain_heads(sf: SourceFile, fn: ast.AST,
                 dispatch_vars: Set[str]) -> List[ast.If]:
    heads: List[ast.If] = []
    for node in walk(fn):
        if not (isinstance(node, ast.If)
                and _tests_dispatch_var(node.test, dispatch_vars)):
            continue
        parent = getattr(node, "_lint_parent", None)
        if isinstance(parent, ast.If) and node in parent.orelse \
                and _tests_dispatch_var(parent.test, dispatch_vars):
            continue  # an elif link, not a chain head
        heads.append(node)
    return heads


def _handles_unknown(stmts: List[ast.stmt]) -> bool:
    for stmt in stmts:
        for node in walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in \
                    registry.FALLTHROUGH_HANDLER_ATTRS:
                return True
    return False


def check_fallthrough(sf: SourceFile, qualname: str,
                      dispatch_vars: Set[str]) -> List[Violation]:
    out: List[Violation] = []
    for fn in sf.functions([qualname]):
        # EVERY chain is checked, not just the last one: a function
        # with two sequential dispatch chains (daemon._route's
        # NODE_SYNC fast path + the main chain) must not hide a silent
        # drop in the earlier chain. An early chain's fallthrough is
        # the code after it, which for non-terminal chains contains the
        # next chain's dispatching calls and passes naturally.
        for chain in _chain_heads(sf, fn, dispatch_vars):
            node: ast.If = chain
            while len(node.orelse) == 1 \
                    and isinstance(node.orelse[0], ast.If) \
                    and _tests_dispatch_var(node.orelse[0].test,
                                            dispatch_vars):
                node = node.orelse[0]
            if node.orelse:
                region = node.orelse
            else:
                # No terminal else: the fallthrough is whatever follows
                # the chain at the same nesting level.
                parent = getattr(chain, "_lint_parent", None)
                body = getattr(parent, "body", [])
                try:
                    idx = body.index(chain)
                    region = body[idx + 1:]
                except ValueError:
                    region = []
            if not _handles_unknown(region):
                out.append(Violation(
                    PASS, sf.relpath, node.lineno,
                    f"dispatch fallthrough in {qualname} drops unknown "
                    f"message types silently — log the msg_type (or "
                    f"bump a drop counter) so a protocol skew is "
                    f"visible",
                    scope=sf.scope_of(fn),
                    key=f"fallthrough:{qualname}"))
    return out


# ---------------------------------------------------------------------------
# unregistered-recv-loop detection
# ---------------------------------------------------------------------------
def _covered_by(qual: str, registered: Set[str]) -> bool:
    """True when `qual` is a registered function or nested inside one
    (inner defs of a registered dispatcher are part of its span)."""
    return any(qual == r or qual.startswith(r + ".")
               for r in registered)


def detect_unregistered_loops(tree: LintTree,
                              all_constants: Set[str]) -> List[Violation]:
    """A function that dispatches over protocol message constants but is
    absent from registry.RECV_LOOPS is a coverage HOLE, not a skip: a
    new recv loop (e.g. a direct-channel handler) must be registered so
    the plane-coverage invariant applies to it. Legitimate non-loop
    dispatchers carry a reasoned registry.NON_LOOP_DISPATCHERS entry."""
    registered_by_file: Dict[str, Set[str]] = {}
    for loop in registry.RECV_LOOPS.values():
        registered_by_file.setdefault(loop["file"], set()).update(
            loop["functions"])
    out: List[Violation] = []
    threshold = registry.RECV_LOOP_DETECT_MIN
    for sf in tree.iter_files():
        if sf.relpath == PROTOCOL_FILE:
            continue
        registered = registered_by_file.get(sf.relpath, set())
        for fn in walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = sf.scope_of(fn)
            if _covered_by(qual, registered):
                continue
            allow = registry.NON_LOOP_DISPATCHERS.get(
                (sf.relpath, qual))
            if allow:
                continue
            per_var: Dict[str, Set[str]] = {}
            for node in walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                if isinstance(node.left, ast.Name):
                    var = node.left.id
                    consts = [c for comp in node.comparators
                              for c in _const_names(comp)]
                else:
                    vars_ = [c.id for c in node.comparators
                             if isinstance(c, ast.Name)]
                    if not vars_:
                        continue
                    var = vars_[0]
                    consts = _const_names(node.left)
                hits = {c for c in consts if c in all_constants}
                if hits:
                    per_var.setdefault(var, set()).update(hits)
            for var, consts in per_var.items():
                if len(consts) >= threshold:
                    out.append(Violation(
                        PASS, sf.relpath, fn.lineno,
                        f"{qual} dispatches {len(consts)} protocol "
                        f"message constants over {var!r} but is not in "
                        f"devtools/lint/registry.py RECV_LOOPS — an "
                        f"unregistered recv loop dodges plane coverage; "
                        f"register it (or add a reasoned "
                        f"NON_LOOP_DISPATCHERS entry)",
                        scope=qual, key=f"unregistered-loop:{qual}"))
                    break  # one violation per function is enough
    return out


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------
def run(tree: LintTree) -> List[Violation]:
    proto = tree.get(PROTOCOL_FILE)
    if proto is None:
        return []  # fixture tree without a protocol module
    planes, out = parse_planes(proto)
    all_constants = set().union(*planes.values())
    out.extend(detect_unregistered_loops(tree, all_constants))

    for loop_name, loop in registry.RECV_LOOPS.items():
        sf = tree.get(loop["file"])
        if sf is None:
            continue
        dv = set(loop["dispatch_vars"])
        fns = sf.functions(loop["functions"])
        if not fns:
            out.append(Violation(
                PASS, loop["file"], 1,
                f"recv loop {loop_name}: none of the registered dispatch "
                f"functions {loop['functions']} exist — update "
                f"devtools/lint/registry.py RECV_LOOPS",
                key=f"loop-missing:{loop_name}"))
            continue
        anchor = min(fn.lineno for fn in fns)
        handled = dispatched_constants(sf, loop["functions"], dv)

        for const in sorted(handled - all_constants):
            out.append(Violation(
                PASS, loop["file"], anchor,
                f"recv loop {loop_name} dispatches {const}, which is not "
                f"a message constant in protocol.py",
                key=f"unknown-const:{loop_name}:{const}"))

        if not loop["relay"]:
            required = planes.get(loop["plane"], set())
            missing = required - handled - set(loop["exempt"])
            for const in sorted(missing):
                out.append(Violation(
                    PASS, loop["file"], anchor,
                    f"recv loop {loop_name} does not dispatch {const} "
                    f"(plane {loop['plane']}); handle it, or register an "
                    f"exemption with a reason in "
                    f"devtools/lint/registry.py",
                    key=f"missing:{loop_name}:{const}"))
            for const, reason in sorted(loop["exempt"].items()):
                if const in handled:
                    out.append(Violation(
                        PASS, loop["file"], anchor,
                        f"stale exemption: {loop_name} now dispatches "
                        f"{const} ({reason!r}) — drop it from the "
                        f"registry",
                        key=f"stale-exempt:{loop_name}:{const}"))

        if loop["fallthrough"]:
            out.extend(check_fallthrough(sf, loop["fallthrough"], dv))
    return out
