"""guarded-by pass.

The field-level data-race tier's static half (dynamic:
``_private/racedebug.py``). lockdep (PR 4) proves lock *ordering*;
this pass proves which lock guards which shared *field*:

1. **Guarded access** — every read/write of a field registered in
   ``registry.GUARDED_FIELDS`` must be lexically under a
   ``with <recv>.<lock_attr>:`` of the owning lock, inside a function
   declared lock-held (``registry.HOLDS_LOCK``), or carry a reasoned
   ``# lint: guarded-by-ok <reason>`` annotation. ``__init__`` is
   exempt (init-then-publish — no other thread can see the object
   yet; the dynamic half's first-thread state is the same exemption).
   A ``with``/holder in an ENCLOSING function does not cover a nested
   ``def`` (it runs later, possibly unlocked) — nested defs register
   their own qualname or annotate.

2. **Lock-held helper inventory** — ``*_locked`` defs in a registered
   class must be declared in ``HOLDS_LOCK`` (a new helper is a new
   obligation), declared helpers must still exist (rot), and every
   lexical call of one must itself sit under the held lock.

3. **Registry/lockdep agreement** — the registered ``lock_attr`` must
   be created in ``__init__`` through the lockdep factory under the
   registered ``lockdep_class`` name, so the static registry and the
   runtime lockset detector name the SAME lock.

4. **Coverage ratchet** — a field assigned in ``__init__`` of a
   registered class but absent from the registry is flagged
   (``unregistered-field``) and baselined like broad-except: new
   fields on the hot concurrent classes must be registered (accesses
   proven) or reason-annotated; the debt only burns down.

Stale ``guarded-by-ok`` annotations (suppressing nothing) are flagged
like protocol-order's.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import registry
from .core import LintTree, SourceFile, Violation, walk

PASS = "guarded-by"
RULE = "guarded-by"

_LOCKDEP_FACTORIES = {"lock", "rlock", "condition"}


def _with_guard(item: ast.withitem) -> Optional[Tuple[str, str]]:
    """``with <recv>.<attr>:`` -> (recv, attr); None otherwise."""
    expr = item.context_expr
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        return expr.value.id, expr.attr
    return None


def _is_write(sf: SourceFile, node: ast.Attribute) -> bool:
    """Store/Del on the attribute itself, or a store through a
    subscript/augmented assignment rooted at it."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    cur: ast.AST = node
    for parent in sf.parents(node):
        if isinstance(parent, ast.Subscript) and parent.value is cur:
            if isinstance(parent.ctx, (ast.Store, ast.Del)):
                return True
            cur = parent
            continue
        if isinstance(parent, ast.AugAssign) and parent.target is cur:
            return True
        break
    return False


def _lock_held(sf: SourceFile, node: ast.AST, recv: str,
               lock_attrs: frozenset,
               holds_lock: Dict[str, Set[str]]) -> bool:
    """Is `node` lexically under ``with <recv>.<attr>:`` for any attr in
    `lock_attrs` (the guard lock plus its aliases — e.g. a Condition
    wrapping it) within its own function frame, or inside a
    HOLDS_LOCK-declared function that holds one? Withs beyond the first
    function boundary belong to a different runtime frame and do not
    count."""
    cur: ast.AST = node
    for parent in sf.parents(node):
        if isinstance(parent, ast.With):
            for item in parent.items:
                guard = _with_guard(item)
                if guard is not None and guard[0] == recv \
                        and guard[1] in lock_attrs:
                    return True
        elif isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            if not isinstance(parent, ast.Lambda):
                held = holds_lock.get(sf.scope_of(parent))
                if held and held & lock_attrs:
                    return True
            return False
        cur = parent
    return False


def _enclosing_func(sf: SourceFile, node: ast.AST) -> Optional[ast.AST]:
    for parent in sf.parents(node):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return parent
    return None


def run(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []

    by_file: Dict[str, Dict[str, Dict[str, Tuple[str, str]]]] = {}
    for (relpath, cls), fields in registry.GUARDED_FIELDS.items():
        by_file.setdefault(relpath, {})[cls] = dict(fields)
    holds_by_file: Dict[str, Dict[str, Set[str]]] = {}
    for (relpath, qualname), attrs in registry.HOLDS_LOCK.items():
        holds_by_file.setdefault(relpath, {})[qualname] = set(attrs)

    for relpath in sorted(set(by_file) | set(holds_by_file)):
        sf = tree.get(relpath)
        class_fields = by_file.get(relpath, {})
        holds_lock = holds_by_file.get(relpath, {})
        if sf is None:
            continue
        used_suppressions: Set[int] = set()

        def suppress(*lines: int) -> bool:
            if sf.suppressed(RULE, *lines):
                used_suppressions.update(
                    ln for ln in lines
                    if sf.suppressions.get(ln, ("", ""))[0] == RULE)
                return True
            return False

        # -- class / field / lock-class rot --------------------------------
        # One scan of the cached node list builds every per-class index
        # the checks below need (re-walking each class subtree made this
        # the slowest pass; the wall-clock pin in test_lint.py budgets
        # the whole suite).
        classes: Dict[str, ast.ClassDef] = {}
        self_attrs_by_cls: Dict[str, Set[str]] = {}
        assigns_by_cls: Dict[str, List[ast.Assign]] = {}
        func_defs: List[ast.AST] = []
        attr_nodes: List[ast.Attribute] = []
        call_nodes: List[ast.Call] = []
        for n in sf.nodes:
            if isinstance(n, ast.Attribute):
                if isinstance(n.value, ast.Name):
                    attr_nodes.append(n)
                    if n.value.id == "self":
                        self_attrs_by_cls.setdefault(
                            sf.scope_of(n).split(".", 1)[0],
                            set()).add(n.attr)
            elif isinstance(n, ast.ClassDef):
                classes.setdefault(n.name, n)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_defs.append(n)
            elif isinstance(n, ast.Assign) and len(n.targets) == 1:
                assigns_by_cls.setdefault(
                    sf.scope_of(n).split(".", 1)[0], []).append(n)
            elif isinstance(n, ast.Call):
                call_nodes.append(n)
        # attr name -> owning classes (for non-self receiver matching)
        field_owners: Dict[str, List[str]] = {}
        # cls -> {lock_attr: frozenset of equivalent guard attrs}
        # (a Condition built over the lock shares its mutex: acquiring
        # either IS holding the guard).
        guard_groups: Dict[str, Dict[str, frozenset]] = {}
        for cls, fields in sorted(class_fields.items()):
            for field in fields:
                field_owners.setdefault(field, []).append(cls)
            node = classes.get(cls)
            if node is None:
                out.append(Violation(
                    PASS, relpath, 1,
                    f"GUARDED_FIELDS registers class {cls} which no "
                    f"longer exists in {relpath} — registry rot",
                    scope="<module>", key=f"stale-guarded-class:{cls}"))
                continue
            seen_attrs = self_attrs_by_cls.get(cls, set())
            # lockdep factory assignments in this class:
            #   self.<attr> = lockdep.lock("<class>")
            # plus Condition aliases over an already-named lock:
            #   self.<attr> = threading.Condition(self.<lock>)
            lock_classes: Dict[str, str] = {}
            aliases: Dict[str, str] = {}
            for a in assigns_by_cls.get(cls, []):
                tgt = a.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                call = a.value
                if not isinstance(call, ast.Call):
                    continue
                fn = call.func
                if isinstance(fn, ast.Attribute) \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id == "lockdep" \
                        and fn.attr in _LOCKDEP_FACTORIES \
                        and call.args \
                        and isinstance(call.args[0], ast.Constant) \
                        and isinstance(call.args[0].value, str):
                    lock_classes[tgt.attr] = call.args[0].value
                elif ((isinstance(fn, ast.Attribute)
                       and fn.attr == "Condition")
                      or (isinstance(fn, ast.Name)
                          and fn.id == "Condition")) \
                        and call.args \
                        and isinstance(call.args[0], ast.Attribute) \
                        and isinstance(call.args[0].value, ast.Name) \
                        and call.args[0].value.id == "self":
                    aliases[tgt.attr] = call.args[0].attr
            def _root(attr: str) -> str:
                seen: Set[str] = set()
                while attr in aliases and attr not in seen:
                    seen.add(attr)
                    attr = aliases[attr]
                return attr
            groups: Dict[str, frozenset] = {}
            for lock_attr in {la for la, _lc in fields.values()}:
                root = _root(lock_attr)
                groups[lock_attr] = frozenset(
                    {lock_attr, root}
                    | {al for al in aliases if _root(al) == root})
                if lock_attr not in lock_classes \
                        and root in lock_classes:
                    lock_classes[lock_attr] = lock_classes[root]
            guard_groups[cls] = groups
            for field, (lock_attr, lockdep_class) in sorted(fields.items()):
                if field not in seen_attrs:
                    out.append(Violation(
                        PASS, relpath, node.lineno,
                        f"registered field {cls}.{field} is never "
                        f"accessed in the class — renamed or deleted; "
                        f"update GUARDED_FIELDS",
                        scope=cls, key=f"stale-guarded-field:{cls}.{field}"))
                got = lock_classes.get(lock_attr)
                if got is None:
                    out.append(Violation(
                        PASS, relpath, node.lineno,
                        f"guard lock {cls}.{lock_attr} (for field "
                        f"{field}) is not created through the lockdep "
                        f"factory in this class — the runtime lockset "
                        f"detector cannot see it; create it via "
                        f"lockdep.lock/rlock/condition",
                        scope=cls, key=f"unnamed-guard-lock:{cls}.{lock_attr}"))
                elif got != lockdep_class:
                    out.append(Violation(
                        PASS, relpath, node.lineno,
                        f"guard lock {cls}.{lock_attr} is lockdep class "
                        f"{got!r} but GUARDED_FIELDS registers "
                        f"{lockdep_class!r} for field {field} — the "
                        f"static registry and the runtime lockset "
                        f"detector must name the SAME lock",
                        scope=cls,
                        key=f"wrong-lock-class:{cls}.{lock_attr}"))

        # -- HOLDS_LOCK inventory (both directions) ------------------------
        qualnames = {sf.scope_of(n) for n in func_defs}
        for qualname in sorted(holds_lock):
            if qualname not in qualnames:
                out.append(Violation(
                    PASS, relpath, 1,
                    f"HOLDS_LOCK registers {qualname} which no longer "
                    f"exists in {relpath} — registry rot",
                    scope="<module>", key=f"stale-holds-lock:{qualname}"))
        for node in func_defs:
            qualname = sf.scope_of(node)
            cls = qualname.split(".", 1)[0]
            if cls in class_fields and node.name.endswith("_locked") \
                    and qualname not in holds_lock:
                if suppress(node.lineno):
                    continue
                out.append(Violation(
                    PASS, relpath, node.lineno,
                    f"{qualname} follows the *_locked convention but "
                    f"has no HOLDS_LOCK entry — declare which lock(s) "
                    f"its callers hold so field accesses inside it are "
                    f"checkable",
                    scope=qualname,
                    key=f"unregistered-locked-helper:{qualname}"))

        # -- calls of lock-held helpers must hold the lock -----------------
        helper_names = {q.rsplit(".", 1)[-1]: q for q in holds_lock}
        for node in call_nodes:
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in helper_names
                    and isinstance(node.func.value, ast.Name)):
                continue
            qualname = helper_names[node.func.attr]
            recv = node.func.value.id
            needed = holds_lock[qualname]
            hgroups = guard_groups.get(qualname.split(".", 1)[0], {})
            scope = sf.scope_of(node)
            # A helper calling a sibling helper under the same holds.
            caller_held = holds_lock.get(scope, set())
            missing = []
            for a in sorted(needed):
                group = hgroups.get(a, frozenset({a}))
                if not (caller_held & group) \
                        and not _lock_held(sf, node, recv, group,
                                           holds_lock):
                    missing.append(a)
            if not missing:
                continue
            if suppress(node.lineno):
                continue
            out.append(Violation(
                PASS, relpath, node.lineno,
                f"call of lock-held helper {qualname}() without "
                f"holding {', '.join(missing)} — take the lock or "
                f"annotate `# lint: {RULE}-ok <reason>`",
                scope=scope, key=f"unguarded-locked-call:{qualname}"))

        # -- guarded field accesses ----------------------------------------
        for node in attr_nodes:
            owners = field_owners.get(node.attr)
            if not owners:
                continue
            recv = node.value.id
            scope = sf.scope_of(node)
            scope_cls = scope.split(".", 1)[0]
            if recv == "self":
                if scope_cls not in class_fields \
                        or node.attr not in class_fields[scope_cls]:
                    continue
                cls = scope_cls
            else:
                # Cross-object access: unambiguous, non-generic names
                # only (mirrors lock-discipline's receiver rules).
                if len(owners) != 1 \
                        or node.attr in registry.GUARDED_GENERIC_ATTRS:
                    continue
                cls = owners[0]
                if scope_cls == cls:
                    # A self-class helper touching another instance
                    # (e.g. merge) still holds only its OWN lock;
                    # keep checking with the receiver name.
                    pass
            lock_attr, _lockdep_class = class_fields[cls][node.attr]
            func = _enclosing_func(sf, node)
            if func is not None and func.name == "__init__" \
                    and sf.scope_of(func) == f"{cls}.__init__" \
                    and recv == "self":
                continue  # init-then-publish: not shared yet
            group = guard_groups.get(cls, {}).get(
                lock_attr, frozenset({lock_attr}))
            if _lock_held(sf, node, recv, group, holds_lock):
                continue
            lines = [node.lineno]
            if func is not None:
                lines.append(func.lineno)
            if suppress(*lines):
                continue
            kind = "write" if _is_write(sf, node) else "read"
            out.append(Violation(
                PASS, relpath, node.lineno,
                f"unguarded {kind} of {cls}.{node.attr} — registered "
                f"as guarded by {cls}.{lock_attr}; take the lock, "
                f"register the function in HOLDS_LOCK, or annotate "
                f"`# lint: {RULE}-ok <reason>`",
                scope=scope, key=f"unguarded-{kind}:{cls}.{node.attr}"))

        # -- coverage ratchet: __init__ fields absent from the registry ----
        for cls, fields in sorted(class_fields.items()):
            node = classes.get(cls)
            if node is None:
                continue
            guard_attrs = {la for la, _lc in fields.values()}
            init = next((n for n in node.body
                         if isinstance(n, ast.FunctionDef)
                         and n.name == "__init__"), None)
            if init is None:
                continue
            seen: Set[str] = set()
            for a in walk(init):
                if not (isinstance(a, ast.Attribute)
                        and isinstance(a.ctx, ast.Store)
                        and isinstance(a.value, ast.Name)
                        and a.value.id == "self"):
                    continue
                attr = a.attr
                if attr in fields or attr in guard_attrs or attr in seen:
                    continue
                seen.add(attr)
                if suppress(a.lineno):
                    continue
                out.append(Violation(
                    PASS, relpath, a.lineno,
                    f"{cls}.{attr} is assigned in __init__ of a "
                    f"guarded class but absent from GUARDED_FIELDS — "
                    f"register it (and prove its accesses) or annotate "
                    f"`# lint: {RULE}-ok <reason>` (coverage ratchet)",
                    scope=f"{cls}.__init__",
                    key=f"unregistered-field:{cls}.{attr}"))

        # -- stale annotations ---------------------------------------------
        for lineno, (rule, reason) in sorted(sf.suppressions.items()):
            if rule != RULE or not reason:
                continue
            if lineno not in used_suppressions:
                out.append(Violation(
                    PASS, relpath, lineno,
                    f"stale `# lint: {RULE}-ok` annotation — it "
                    f"suppresses nothing; remove it or fix the drift",
                    scope="<module>", key=f"stale-annotation:{lineno}"))
    return out
