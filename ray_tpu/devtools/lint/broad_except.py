"""broad-except pass.

Invariant: a ``except Exception:`` (or bare ``except:``) whose body
does NOTHING — pass/continue/return — is forbidden in ``_private/``.
Silent swallows in the runtime core hide real failures (a dropped
completion, a dead-letter reply) behind happy-path behavior; at minimum
a swallow must debug-log or bump a drop counter, and a deliberately
silent one must carry ``# lint: broad-except-ok <reason>`` on the
``except`` line so the "why it is safe to ignore" survives review.

Handlers that DO something (assign a fallback, reply an error, log) are
not flagged — the pass targets pure swallows only.
"""

from __future__ import annotations

import ast
from typing import List

from . import registry
from .core import LintTree, Violation, walk

PASS = "broad-except"
RULE = "broad-except"

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Attribute):
        return t.attr in _BROAD
    if isinstance(t, ast.Tuple):
        return any(_is_broad(ast.ExceptHandler(type=e, name=None, body=[]))
                   for e in t.elts)
    return False


def _is_pure_swallow(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or isinstance(stmt.value, ast.Constant)):
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Constant):
            continue  # docstring/ellipsis
        return False
    return True


def run(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    for sf in tree.iter_files(registry.BROAD_EXCEPT_PREFIX):
        for node in walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or not _is_pure_swallow(node.body):
                continue
            if sf.suppressed(RULE, node.lineno):
                continue
            caught = "bare except" if node.type is None \
                else "except " + ast.unparse(node.type)
            out.append(Violation(
                PASS, sf.relpath, node.lineno,
                f"silent swallow ({caught}: pass) in the runtime core — "
                f"debug-log or bump a drop counter, or annotate "
                f"`# lint: {RULE}-ok <reason>` on the except line",
                scope=sf.scope_of(node), key=f"swallow:{caught}"))
    return out
