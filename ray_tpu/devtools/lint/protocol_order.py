"""protocol-order pass.

Invariant: frames are SENT in a legal session order, not merely
dispatched somewhere (protocol-coverage's job). Three mechanical
properties of the wire protocol, checked against the declarative model
in protocol_model.py:

  * **send legality** — every send site's constant must be a legal
    transition from the states its enclosing function is registered to
    run in (registry.PROTOCOL_SEND_FUNCS, the RECV_LOOPS dual). A send
    from an unregistered function fails: like an unregistered recv
    loop, it would dodge the ordering contract.
  * **response paths** — every constant sent through a request wrapper
    must be registered in protocol_model.REQUESTS, and each registered
    request's response constant must actually be dispatched by the
    requester's recv loop (verified against RECV_LOOPS spans) — a
    request whose reply nothing consumes hangs its future forever.
  * **no send after teardown** — a send on a connection lexically after
    that same connection's ``close()`` in one function is a frame into
    a dead socket.

Model rot is checked both ways: a plane constant no session models is
a violation (new constants register against the DFA on day one), and a
model entry naming a constant protocol.py no longer defines is too.
Escape hatch: ``# lint: protocol-order-ok <reason>`` on the send line;
an annotation that suppresses nothing is itself flagged (stale).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import protocol_model, registry
from .core import LintTree, SourceFile, Violation, walk
from .protocol_coverage import PROTOCOL_FILE, dispatched_constants, \
    parse_planes

PASS = "protocol-order"
RULE = "protocol-order"

_REQUEST_ATTRS = frozenset({"request", "_request"})


# ---------------------------------------------------------------------------
# send-site discovery (shared with payload_schema)
# ---------------------------------------------------------------------------
def send_const(call: ast.Call) -> Optional[str]:
    """The protocol-constant name a send call names, if any: first
    positional arg shaped ``P.CONST`` or bare ``CONST``."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr in registry.PROTOCOL_SEND_ATTRS
            and call.args):
        return None
    a = call.args[0]
    if isinstance(a, ast.Attribute) and a.attr == a.attr.upper() \
            and isinstance(a.value, ast.Name):
        return a.attr
    if isinstance(a, ast.Name) and a.id == a.id.upper():
        return a.id
    return None


def iter_send_sites(sf: SourceFile, consts: Set[str]
                    ) -> Iterable[Tuple[ast.Call, str, str]]:
    """Yield (call, CONST, enclosing qualname) for every send of a
    protocol constant in `sf`."""
    for node in walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        const = send_const(node)
        if const is not None and const in consts:
            yield node, const, sf.scope_of(node)


def lookup_send_entries(relpath: str, qual: str):
    """PROTOCOL_SEND_FUNCS entries for `qual`, walking up dotted
    prefixes so nested defs inherit their enclosing registration."""
    parts = qual.split(".")
    for end in range(len(parts), 0, -1):
        hit = registry.PROTOCOL_SEND_FUNCS.get(
            (relpath, ".".join(parts[:end])))
        if hit is not None:
            return hit
    return None


# ---------------------------------------------------------------------------
# suppression tracking (with rot detection)
# ---------------------------------------------------------------------------
class Suppressions:
    """Per-run ledger of which ``<rule>-ok`` annotations earned their
    keep; the leftovers are stale (rot detection). Shared with the
    payload-schema pass."""

    def __init__(self, pass_name: str, rule: str) -> None:
        self.pass_name = pass_name
        self.rule = rule
        self.used: Set[Tuple[str, int]] = set()

    def consume(self, sf: SourceFile, node: ast.AST) -> bool:
        lines = range(node.lineno, (node.end_lineno or node.lineno) + 1)
        hit = False
        for ln in lines:
            entry = sf.suppressions.get(ln)
            if entry and entry[0] == self.rule and entry[1]:
                self.used.add((sf.relpath, ln))
                hit = True
        return hit

    def stale(self, tree: LintTree) -> List[Violation]:
        out: List[Violation] = []
        for sf in tree.iter_files():
            if sf.relpath.startswith("devtools/lint"):
                continue  # the linter's own docs MENTION the pattern
            for ln, (rule, reason) in sorted(sf.suppressions.items()):
                if rule != self.rule or not reason:
                    continue
                if (sf.relpath, ln) in self.used:
                    continue
                out.append(Violation(
                    self.pass_name, sf.relpath, ln,
                    f"stale annotation: this 'lint: {self.rule}-ok' "
                    f"comment suppressed nothing in this run — the "
                    f"deviation it documented is gone; remove it",
                    scope=_scope_at_line(sf, ln),
                    key="stale-annotation"))
        return out


def _scope_at_line(sf: SourceFile, line: int) -> str:
    best = "<module>"
    best_span = None
    for node in walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = node.end_lineno or node.lineno
        if node.lineno <= line <= end:
            span = end - node.lineno
            if best_span is None or span < best_span:
                best, best_span = sf.scope_of(node), span
    return best


# ---------------------------------------------------------------------------
# teardown analysis
# ---------------------------------------------------------------------------
def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None  # call/subscript receivers: not a stable name


def _close_sites(fn: ast.AST) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in registry.PROTOCOL_CLOSE_ATTRS \
                and not node.args:
            recv = _dotted(node.func.value)
            if recv is not None:
                out.append((node.lineno, recv))
    return out


def _prefix_match(a: str, b: str) -> bool:
    return a == b or a.startswith(b + ".") or b.startswith(a + ".")


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------
def _const_lines(proto: SourceFile) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in proto.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.lineno
    return out


def _model_constants() -> Set[str]:
    names = protocol_model.all_modeled_constants()
    for const, req in protocol_model.REQUESTS.items():
        names.add(const)
        names.add(req["response"])
    names.update(protocol_model.PAYLOADS)
    return names


def _describe_entries(entries) -> str:
    return ", ".join(f"{s}/{r}@{'|'.join(states)}"
                     for s, r, states in entries)


def run(tree: LintTree) -> List[Violation]:
    proto = tree.get(PROTOCOL_FILE)
    if proto is None:
        return []  # fixture tree without a protocol module
    planes, _ = parse_planes(proto)  # plane parse errors belong to
    all_consts: Set[str] = set().union(*planes.values())  # coverage pass
    lines = _const_lines(proto)
    out: List[Violation] = []
    sup = Suppressions(PASS, RULE)

    # -- model <-> protocol.py drift ------------------------------------
    for name in sorted(_model_constants() - all_consts):
        out.append(Violation(
            PASS, PROTOCOL_FILE, 1,
            f"protocol model references {name}, which protocol.py no "
            f"longer defines — prune it from "
            f"devtools/lint/protocol_model.py",
            key=f"unknown-const:{name}"))
    modeled = protocol_model.all_modeled_constants()
    for name in sorted(all_consts - modeled):
        out.append(Violation(
            PASS, PROTOCOL_FILE, lines.get(name, 1),
            f"message constant {name} belongs to no session DFA — new "
            f"constants register their ordering contract in "
            f"devtools/lint/protocol_model.py SESSIONS on day one",
            key=f"unmodeled-constant:{name}"))

    # -- send sites ------------------------------------------------------
    for sf in tree.iter_files():
        if sf.relpath == PROTOCOL_FILE:
            continue
        close_cache: Dict[ast.AST, List[Tuple[int, str]]] = {}
        for call, const, qual in iter_send_sites(sf, all_consts):
            entries = lookup_send_entries(sf.relpath, qual)
            if entries is None:
                if not sup.consume(sf, call):
                    out.append(Violation(
                        PASS, sf.relpath, call.lineno,
                        f"{qual} sends {const} but is not registered in "
                        f"devtools/lint/registry.py PROTOCOL_SEND_FUNCS "
                        f"— an unregistered send site dodges the "
                        f"session-ordering contract",
                        scope=qual, key=f"unregistered-send:{const}"))
                continue
            legal = False
            for session_name, role, states in entries:
                sends = protocol_model.SESSIONS[session_name]["roles"][
                    role]["sends"]
                if const in sends and set(states) & set(sends[const]):
                    legal = True
                    break
            if not legal and not sup.consume(sf, call):
                out.append(Violation(
                    PASS, sf.relpath, call.lineno,
                    f"{qual} sends {const}, which is not a legal send "
                    f"for any of its registered session states "
                    f"({_describe_entries(entries)}) — out-of-order "
                    f"or wrong-role frame",
                    scope=qual, key=f"illegal-send:{const}"))

            # request wrappers must have a registered response path
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _REQUEST_ATTRS \
                    and const not in protocol_model.REQUESTS \
                    and not sup.consume(sf, call):
                out.append(Violation(
                    PASS, sf.relpath, call.lineno,
                    f"{qual} sends {const} through a request wrapper "
                    f"but the constant has no protocol_model.REQUESTS "
                    f"entry — its response path is unverified",
                    scope=qual, key=f"no-response-path:{const}"))

            # send lexically after the connection's close()
            fn = next((p for p in sf.parents(call)
                       if isinstance(p, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))), None)
            recv = _dotted(call.func.value) \
                if isinstance(call.func, ast.Attribute) else None
            if fn is not None and recv is not None:
                if fn not in close_cache:
                    close_cache[fn] = _close_sites(fn)
                for close_line, close_recv in close_cache[fn]:
                    if close_line < call.lineno \
                            and _prefix_match(recv, close_recv):
                        if not sup.consume(sf, call):
                            out.append(Violation(
                                PASS, sf.relpath, call.lineno,
                                f"{qual} sends {const} on {recv!r} "
                                f"after closing it at line "
                                f"{close_line} — a frame into a dead "
                                f"connection",
                                scope=qual,
                                key=f"send-after-teardown:{const}"))
                        break

    # -- every registered request's response must be consumed ------------
    for const, req in sorted(protocol_model.REQUESTS.items()):
        loop_name = req["loop"]
        if loop_name is None:
            if not req.get("reason"):
                out.append(Violation(
                    PASS, PROTOCOL_FILE, lines.get(const, 1),
                    f"REQUESTS[{const}] registers no response loop and "
                    f"no reason — name the recv loop that dispatches "
                    f"{req['response']} or document why none does",
                    key=f"response-unverified:{const}"))
            continue
        loop = registry.RECV_LOOPS.get(loop_name)
        if loop is None:
            out.append(Violation(
                PASS, PROTOCOL_FILE, lines.get(const, 1),
                f"REQUESTS[{const}] names recv loop {loop_name!r}, "
                f"which is not in registry.RECV_LOOPS",
                key=f"response-loop-missing:{const}"))
            continue
        sf = tree.get(loop["file"])
        if sf is None:
            continue  # fixture tree without the loop's file
        handled = dispatched_constants(sf, loop["functions"],
                                       set(loop["dispatch_vars"]))
        if req["response"] not in handled:
            out.append(Violation(
                PASS, loop["file"], 1,
                f"request {const} expects response {req['response']} "
                f"from recv loop {loop_name}, but that loop's dispatch "
                f"span never handles it — the requester's future can "
                f"never resolve",
                key=f"response-undispatched:{const}"))

    out.extend(sup.stale(tree))
    return out
