"""gate-discipline pass.

Three invariants keeping the debug planes honest:

1. **Site registry** — every ``fault.fire("<site>", ...)`` names a
   literal site that exists in ``fault.SITES`` (parsed from
   ``_private/fault.py``, never imported). A typo'd site would silently
   never inject; a dynamic site name can't be audited.

2. **Falsy-flag gating** — every instrumentation helper call
   (``fault.fire`` and the ``_ops``-bumping module functions of
   ``_private/telemetry.py``) sits lexically under an
   ``if <plane>.enabled`` guard, so the disabled hot path pays exactly
   one dict lookup (the perf_smoke contract). Helpers called through an
   indirect gate annotate ``# lint: ungated-instrumentation-ok <why>``.

3. **Globally unique metric names** — a metric name is created with one
   kind in one file; the registry dedups by name at runtime, so a
   second definition silently aliases the first (wrong kind = corrupt
   exposition, two owners = samples attributed to the wrong subsystem).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import registry
from .core import LintTree, SourceFile, Violation, walk

PASS = "gate-discipline"
RULE_UNGATED = "ungated-instrumentation"

FAULT_FILE = "_private/fault.py"

_METRIC_CTORS = {"Counter": "counter", "Gauge": "gauge",
                 "Histogram": "histogram"}


def parse_fault_sites(sf: SourceFile) -> Set[str]:
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITES" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return {e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return set()


def parse_gated_helpers(sf: SourceFile) -> Set[str]:
    """Module-level telemetry functions that bump the ``_ops``
    instrumentation counter — exactly the ones that must be gated."""
    out: Set[str] = set()
    for node in sf.tree.body:
        if isinstance(node, ast.FunctionDef):
            for inner in walk(node):
                if isinstance(inner, ast.Global) and "_ops" in inner.names:
                    out.add(node.name)
                    break
    return out


def _implies_enabled(test: ast.AST, module: str, want_true: bool) -> bool:
    """Does this branch condition imply ``<module>.enabled`` is truthy?
    ``want_true``: whether the branch under consideration is taken when
    `test` evaluates true (if-body) or false (else-branch). Polarity-
    aware, so ``if not telemetry.enabled: <call>`` does NOT count as
    gated while its else branch does — the inverted-gate bug (telemetry
    running only when OFF) must not pass the lint."""
    if isinstance(test, ast.Attribute) and test.attr == "enabled" \
            and isinstance(test.value, ast.Name) \
            and test.value.id == module:
        return want_true
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _implies_enabled(test.operand, module, not want_true)
    if isinstance(test, ast.BoolOp):
        return any(_implies_enabled(v, module, want_true)
                   for v in test.values)
    return False


def _is_gated(sf: SourceFile, call: ast.Call, module: str) -> bool:
    """True when an ancestor ``if``/ternary branch implies
    ``<module>.enabled`` — the SAME plane module as the call (a
    ``fault.enabled`` guard does not gate a telemetry helper), with the
    branch (body vs else) and negation taken into account."""
    prev: ast.AST = call
    for parent in sf.parents(call):
        if isinstance(parent, (ast.If, ast.While)):
            in_body = any(prev is s for s in parent.body)
            in_orelse = not isinstance(parent, ast.While) and any(
                prev is s for s in parent.orelse)
            if in_body and _implies_enabled(parent.test, module, True):
                return True
            if in_orelse and _implies_enabled(parent.test, module, False):
                return True
        elif isinstance(parent, ast.IfExp):
            if prev is parent.body \
                    and _implies_enabled(parent.test, module, True):
                return True
            if prev is parent.orelse \
                    and _implies_enabled(parent.test, module, False):
                return True
        prev = parent
    return False


def _plane_call(call: ast.Call, module: str,
                names: Set[str]) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == module and fn.attr in names:
        return fn.attr
    return None


def run(tree: LintTree) -> List[Violation]:
    out: List[Violation] = []
    fault_sf = tree.get(FAULT_FILE)
    sites = parse_fault_sites(fault_sf) if fault_sf else set()
    # Per-module helper sets parsed from each plane's impl file (the
    # `_ops`-bumping functions — exactly the ones that must be gated).
    module_helpers: Dict[str, Set[str]] = {}
    for module, relpath in registry.GATED_HELPER_FILES.items():
        sf = tree.get(relpath)
        if sf is not None:
            module_helpers[module] = parse_gated_helpers(sf)

    metric_defs: Dict[str, List[Tuple[str, int, str]]] = {}

    for sf in tree.iter_files():
        impl_file = sf.relpath in registry.GATE_IMPL_FILES
        for node in walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue

            # -- fault.fire site validity + gating ---------------------
            if fault_sf is not None \
                    and _plane_call(node, "fault", {"fire"}):
                if not impl_file:
                    arg = node.args[0] if node.args else None
                    if not (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)):
                        out.append(Violation(
                            PASS, sf.relpath, node.lineno,
                            "fault.fire() site must be a string literal "
                            "(auditable against fault.SITES)",
                            scope=sf.scope_of(node), key="dynamic-site"))
                    elif arg.value not in sites:
                        out.append(Violation(
                            PASS, sf.relpath, node.lineno,
                            f"fault.fire site {arg.value!r} is not in "
                            f"fault.SITES — a typo'd site never "
                            f"injects; register it or fix the name",
                            scope=sf.scope_of(node),
                            key=f"unknown-site:{arg.value}"))
                    if not _is_gated(sf, node, "fault") \
                            and not sf.suppressed(RULE_UNGATED,
                                                  node.lineno):
                        out.append(Violation(
                            PASS, sf.relpath, node.lineno,
                            "fault.fire() outside an `if fault.enabled` "
                            "guard — the disabled hot path must pay one "
                            "dict lookup, not a function call",
                            scope=sf.scope_of(node),
                            key="ungated:fault.fire"))

            # -- gated-plane helper gating (telemetry, tracing) --------
            for module, helpers in module_helpers.items():
                helper = _plane_call(node, module, helpers) \
                    if helpers else None
                if helper and not impl_file \
                        and not _is_gated(sf, node, module) \
                        and not sf.suppressed(RULE_UNGATED, node.lineno):
                    out.append(Violation(
                        PASS, sf.relpath, node.lineno,
                        f"{module}.{helper}() outside an "
                        f"`if {module}.enabled` guard (annotate "
                        f"`# lint: {RULE_UNGATED}-ok <why>` when gated "
                        f"indirectly)",
                        scope=sf.scope_of(node),
                        key=f"ungated:{module}.{helper}"))

            # -- metric definitions ------------------------------------
            kind = None
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "_metric" \
                    or isinstance(fn, ast.Attribute) \
                    and fn.attr == "_metric":
                if len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Constant):
                    kind = str(node.args[1].value)
            elif (isinstance(fn, ast.Name) and fn.id in _METRIC_CTORS):
                kind = _METRIC_CTORS[fn.id]
            elif (isinstance(fn, ast.Attribute)
                  and fn.attr in _METRIC_CTORS
                  and isinstance(fn.value, ast.Name)):
                kind = _METRIC_CTORS[fn.attr]
            if kind and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                metric_defs.setdefault(node.args[0].value, []).append(
                    (sf.relpath, node.lineno, kind))

    # -- global metric-name uniqueness ---------------------------------
    for name, defs in sorted(metric_defs.items()):
        files = {d[0] for d in defs}
        kinds = {d[2] for d in defs}
        if len(files) <= 1 and len(kinds) <= 1:
            continue
        detail = "kinds " + "/".join(sorted(kinds)) \
            if len(kinds) > 1 else "files " + ", ".join(sorted(files))
        for relpath, lineno, _kind in defs:
            out.append(Violation(
                PASS, relpath, lineno,
                f"metric {name!r} is defined in multiple places "
                f"({detail}) — the registry dedups by name, so one "
                f"definition silently wins; metric names must be "
                f"globally unique with one kind",
                key=f"dup-metric:{name}"))
    return out
