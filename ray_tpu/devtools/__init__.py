"""Developer tooling for the ray_tpu codebase (not part of the runtime).

Nothing under this package is imported by ``ray_tpu`` at runtime; the
modules here are pure-stdlib so CI can run them without pulling in jax
or the native store.
"""
