"""ray_tpu.job — job submission: run driver scripts inside the cluster.

Reference parity: python/ray/dashboard/modules/job/ — JobSubmissionClient
(sdk.py), JobManager/JobSupervisor (job_manager.py, job_supervisor.py:
a supervisor actor per job runs the entrypoint as a subprocess with the
job's runtime env, captures logs, and reports status to the GCS KV
store).

    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint="python train.py",
        runtime_env={"working_dir": "./project"})
    client.get_job_status(job_id)   # PENDING/RUNNING/SUCCEEDED/FAILED
    client.get_job_logs(job_id)
"""
import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

# statuses (reference: job/common.py JobStatus)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

_KV_NS = "job"


class JobSupervisor:
    """Per-job supervisor actor (reference: job_supervisor.py JobSupervisor).

    Runs the entrypoint as a shell subprocess, streams output to a log
    file, updates job status in the GCS KV store."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[Dict], metadata: Optional[Dict]):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.metadata = metadata or {}
        self.proc = None
        self.log_path = os.path.join(
            "/tmp", f"ray_tpu_job_{job_id}.log")
        self._set_status(PENDING)

    def _set_status(self, status: str, return_code: Optional[int] = None):
        from ray_tpu._private import state
        rt = state.current()
        info = {"job_id": self.job_id, "status": status,
                "entrypoint": self.entrypoint, "metadata": self.metadata,
                "return_code": return_code, "updated_at": time.time(),
                "log_path": self.log_path}
        rt.gcs_request("kv_put", key=self.job_id,
                       value=json.dumps(info).encode(), namespace=_KV_NS)

    def run(self) -> str:
        """Blocks until the entrypoint exits (driver of the job)."""
        import subprocess
        env = dict(os.environ)
        env.update(self.runtime_env.get("env_vars", {}))
        env["RAY_TPU_JOB_ID"] = self.job_id
        cwd = self.runtime_env.get("working_dir") or os.getcwd()
        self._set_status(RUNNING)
        with open(self.log_path, "wb") as log_f:
            self.proc = subprocess.Popen(
                self.entrypoint, shell=True, env=env, cwd=cwd,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)
            rc = self.proc.wait()
        self._set_status(SUCCEEDED if rc == 0 else
                         (STOPPED if rc == -15 else FAILED), rc)
        return SUCCEEDED if rc == 0 else FAILED

    def stop(self) -> bool:
        if self.proc is not None and self.proc.poll() is None:
            import signal
            # Kill the whole process group (entrypoint may spawn children).
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
            return True
        return False

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Reference: dashboard/modules/job/sdk.py JobSubmissionClient (the
    local-cluster path; there is no separate REST head here — the driver
    process talks to the runtime directly)."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)

    def _kv(self, op, **kw):
        from ray_tpu._private import state
        return state.current().gcs_request(op, namespace=_KV_NS, **kw)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict] = None,
                   metadata: Optional[Dict] = None,
                   submission_id: Optional[str] = None,
                   entrypoint_num_cpus: float = 0) -> str:
        if runtime_env:
            from ray_tpu._private import runtime_env as re_mod
            re_mod.validate(runtime_env)
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        if self._kv("kv_get", key=job_id) is not None:
            raise ValueError(f"Job {job_id} already exists")
        # max_concurrency: run() blocks for the job's lifetime; stop()/ping()
        # must still get through (reference: the supervisor actor serves
        # stop while polling the child, job_supervisor.py).
        supervisor = ray_tpu.remote(JobSupervisor).options(
            name=f"_job_supervisor_{job_id}",
            num_cpus=entrypoint_num_cpus, max_concurrency=4).remote(
                job_id, entrypoint, runtime_env, metadata)
        ray_tpu.get(supervisor.ping.remote())  # surface ctor errors
        supervisor.run.remote()  # fire and forget; status lands in KV
        return job_id

    def _info(self, job_id: str) -> Dict[str, Any]:
        raw = self._kv("kv_get", key=job_id)
        if raw is None:
            raise ValueError(f"No job with id {job_id}")
        return json.loads(raw)

    def get_job_status(self, job_id: str) -> str:
        return self._info(job_id)["status"]

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return self._info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        info = self._info(job_id)
        try:
            with open(info["log_path"], "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def list_jobs(self) -> List[Dict[str, Any]]:
        out = []
        for key in self._kv("kv_keys", prefix="raysubmit_"):
            try:
                out.append(self._info(key))
            except ValueError:
                pass
        return out

    def stop_job(self, job_id: str) -> bool:
        info = self._info(job_id)  # raises for unknown job
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor_{job_id}")
            return ray_tpu.get(sup.stop.remote())
        except Exception:
            return False

    def delete_job(self, job_id: str) -> bool:
        info = self._info(job_id)
        if info["status"] in (RUNNING, PENDING):
            raise RuntimeError(f"Cannot delete running job {job_id}")
        self._kv("kv_del", key=job_id)
        try:
            os.unlink(info["log_path"])
        except OSError:
            pass
        return True

    def wait_until_finish(self, job_id: str, timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            time.sleep(0.25)
        raise TimeoutError(f"Job {job_id} still "
                           f"{self.get_job_status(job_id)} after {timeout}s")


__all__ = ["FAILED", "JobSubmissionClient", "JobSupervisor", "PENDING",
           "RUNNING", "STOPPED", "SUCCEEDED"]
