"""Online LLM serving: KV-cache decode streamed through Serve.

Parity role: the reference serves LLMs by deploying external engines
(vLLM) on its actors and streaming tokens through Serve's response path;
here the engine is native — models.generate's jitted prefill/decode
steps inside a Serve replica, tokens streamed to clients chunk by chunk
(Serve's streaming response path). `num_tpus=1` in the deployment's
ray_actor_options pins a chip per replica.

Zero-egress tokenizer: a byte-level vocabulary (ids 0-255 + BOS) so the
demo runs without downloaded vocabularies; swap `tokenizer=` for a real
one in production.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

BOS = 256


class ByteTokenizer:
    """Byte-level tokenizer (vocab 257: bytes + BOS)."""

    vocab_size = 257

    def encode(self, text: str):
        return [BOS] + list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode(
            "utf-8", errors="replace")


class LLMEngine:
    """Jitted prefill + decode wrapper around a GPT-family model
    (construct once per replica; generation streams tokens)."""

    def __init__(self, cfg=None, params=None, tokenizer=None,
                 seed: int = 0):
        import jax

        from ..models import GPTConfig, gpt_init

        self.tokenizer = tokenizer or ByteTokenizer()
        self.cfg = cfg or GPTConfig(
            vocab_size=max(ByteTokenizer.vocab_size, 272),
            d_model=256, n_heads=8, n_layers=4, d_ff=1024,
            max_seq_len=512)
        self.params = params if params is not None else gpt_init(
            jax.random.PRNGKey(seed), self.cfg)

    def stream(self, prompt: str, max_new_tokens: int = 64,
               temperature: float = 0.0) -> Iterator[str]:
        """Yield decoded text fragments token by token. Multi-byte
        UTF-8 sequences are buffered across tokens (an incremental
        decoder), and over-long prompts keep their TAIL so the model
        conditions on the most recent context."""
        import codecs

        import numpy as np

        from ..models.generate import generate

        encoded = self.tokenizer.encode(prompt)
        # Leave room for at least one generated token.
        keep = self.cfg.max_seq_len - max(1, min(max_new_tokens, 16))
        if len(encoded) > keep:
            encoded = encoded[-keep:]
        ids = np.asarray([encoded], np.int32)
        budget = self.cfg.max_seq_len - ids.shape[1]
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        for token in generate(self.params, self.cfg, ids,
                              max_new_tokens=min(max_new_tokens, budget),
                              temperature=temperature):
            t = int(token[0])
            piece = decoder.decode(bytes([t])) if 0 <= t < 256 else ""
            if piece:
                yield piece
        tail = decoder.decode(b"", final=True)
        if tail:
            yield tail

    def complete(self, prompt: str, max_new_tokens: int = 64,
                 temperature: float = 0.0) -> str:
        return "".join(self.stream(prompt, max_new_tokens, temperature))


def build_llm_app(cfg=None, params=None, *, num_replicas: int = 1,
                  num_tpus: float = 0, continuous_batching: bool = False,
                  max_batch: int = 8):
    """Serve application: POST {"prompt": ..., "max_tokens": ...,
    "stream": bool} — streaming responses ride Serve's chunked path.

    ``continuous_batching=True`` backs each replica with ONE shared
    ContinuousBatchingEngine (llm/continuous.py): concurrent requests
    decode together in a slot-reuse KV batch, so a late request joins
    the running decode instead of queueing behind it."""
    from .. import serve

    actor_opts: Dict[str, Any] = {}
    if num_tpus:
        actor_opts["num_tpus"] = num_tpus

    @serve.deployment(num_replicas=num_replicas,
                      ray_actor_options=actor_opts or None,
                      max_ongoing_requests=max(16, 2 * max_batch))
    class LLMServer:
        def __init__(self):
            if continuous_batching:
                from .continuous import ContinuousBatchingEngine
                self.engine = ContinuousBatchingEngine(
                    cfg=cfg, params=params, max_batch=max_batch)
                self._stream = self.engine.submit
            else:
                self.engine = LLMEngine(cfg=cfg, params=params)
                self._stream = self.engine.stream

        def _lazy_stream(self, prompt, max_tokens, temperature):
            # Defer the submit to first iteration: the serve replica's
            # dynamic-generator handshake re-runs the handler once on
            # the first stream=True request (StreamingResponseRequired
            # retry), and an EAGER submit there would enqueue a second,
            # abandoned copy that burns a continuous-batching KV slot
            # for its whole token budget.
            yield from self._stream(prompt, max_tokens, temperature)

        def __call__(self, request):
            body = request.get("body") or {}
            prompt = str(body.get("prompt", ""))
            try:
                max_tokens = max(1, min(int(body.get("max_tokens", 32)),
                                        self.engine.cfg.max_seq_len))
                temperature = max(0.0,
                                  float(body.get("temperature", 0.0)))
            except (TypeError, ValueError):
                return {"error": "max_tokens must be an int and "
                        "temperature a float"}
            if body.get("stream"):
                return self._lazy_stream(prompt, max_tokens,
                                         temperature)
            return {"text": "".join(
                self._stream(prompt, max_tokens, temperature))}

        def generate_stream(self, prompt: str, max_tokens: int = 32,
                            temperature: float = 0.0):
            yield from self._stream(prompt, max_tokens, temperature)

    return LLMServer.bind()
