"""Batch pipeline stages (reference: llm/_internal/batch/stages/).

Every stage is a map_batches-compatible callable over columnar dict
batches. Stateful stages (tokenizer, model) are callable CLASSES so the
data layer hosts them in an actor pool and state is built once per actor
(reference: stages run as Ray Data actor-pool UDFs).
"""
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


class ChatTemplateStage:
    """Render chat messages into a prompt string (reference:
    chat_template_stage.py). Uses the tokenizer's template when a model
    id is given, else a plain role-tagged format."""

    def __init__(self, model: Optional[str] = None,
                 input_column: str = "messages",
                 output_column: str = "prompt"):
        self._in = input_column
        self._out = output_column
        self._tok = None
        if model is not None:
            from transformers import AutoTokenizer
            self._tok = AutoTokenizer.from_pretrained(model)

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        prompts = []
        for messages in batch[self._in]:
            if isinstance(messages, str):
                messages = json.loads(messages)
            if self._tok is not None:
                prompts.append(self._tok.apply_chat_template(
                    messages, tokenize=False, add_generation_prompt=True))
            else:
                prompts.append("\n".join(
                    f"<|{m['role']}|>: {m['content']}" for m in messages
                ) + "\n<|assistant|>:")
        out = dict(batch)
        out[self._out] = prompts
        return out


class TokenizeStage:
    """Prompt -> token ids (reference: tokenize_stage.py). Falls back to
    a built-in byte tokenizer when no model id is given (no downloads)."""

    def __init__(self, model: Optional[str] = None,
                 input_column: str = "prompt",
                 output_column: str = "tokens",
                 max_length: int = 512):
        self._in, self._out = input_column, output_column
        self._max = max_length
        self._tok = None
        if model is not None:
            from transformers import AutoTokenizer
            self._tok = AutoTokenizer.from_pretrained(model)

    def _encode(self, text: str) -> List[int]:
        if self._tok is not None:
            return self._tok.encode(text)[: self._max]
        return list(text.encode("utf-8"))[: self._max]

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(batch)
        out[self._out] = [np.asarray(self._encode(p), np.int32)
                          for p in batch[self._in]]
        return out


class DetokenizeStage:
    """Token ids -> text (reference: detokenize stage)."""

    def __init__(self, model: Optional[str] = None,
                 input_column: str = "generated_tokens",
                 output_column: str = "generated_text"):
        self._in, self._out = input_column, output_column
        self._tok = None
        if model is not None:
            from transformers import AutoTokenizer
            self._tok = AutoTokenizer.from_pretrained(model)

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        texts = []
        for toks in batch[self._in]:
            toks = [int(t) for t in toks]
            if self._tok is not None:
                texts.append(self._tok.decode(toks))
            else:
                texts.append(bytes(t % 256 for t in toks).decode(
                    "utf-8", errors="replace"))
        out = dict(batch)
        out[self._out] = texts
        return out


class HttpRequestStage:
    """POST each row to an endpoint (reference: http_request_stage.py —
    the hosted-LLM path). Serial per batch; no egress in tests."""

    def __init__(self, url: str, payload_column: str = "payload",
                 output_column: str = "response",
                 headers: Optional[Dict[str, str]] = None,
                 timeout_s: float = 30.0):
        self._url = url
        self._in, self._out = payload_column, output_column
        self._headers = dict(headers or {})
        self._timeout = timeout_s

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import urllib.request
        responses = []
        for payload in batch[self._in]:
            data = json.dumps(payload).encode() \
                if not isinstance(payload, (bytes, str)) else (
                    payload.encode() if isinstance(payload, str) else payload)
            req = urllib.request.Request(
                self._url, data=data,
                headers={"Content-Type": "application/json",
                         **self._headers})
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                responses.append(r.read().decode())
        out = dict(batch)
        out[self._out] = responses
        return out


class GPTInferenceStage:
    """TPU-native generation stage: greedy decode with the in-repo GPT
    (models/gpt.py) — prompts padded to power-of-two buckets so the
    jitted decode compiles once per bucket (the XLA serving rule)."""

    def __init__(self, config=None, params=None, max_new_tokens: int = 8,
                 input_column: str = "tokens",
                 output_column: str = "generated_tokens"):
        import jax
        from ..models.gpt import GPTConfig, gpt_forward, gpt_init
        self._cfg = config or GPTConfig.tiny()
        key = jax.random.PRNGKey(0)
        self._params = params if params is not None else gpt_init(
            key, self._cfg)
        self._max_new = max_new_tokens
        self._in, self._out = input_column, output_column

        import jax.numpy as jnp

        def _decode(params, tokens):
            # tokens: [B, T] padded; greedy argmax loop via lax.scan over
            # a fixed number of new tokens (static shapes for XLA).
            def step(toks, _):
                logits = gpt_forward(params, toks, self._cfg)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1)
                toks = jnp.concatenate(
                    [toks[:, 1:], nxt[:, None]], axis=1)
                return toks, nxt

            _, news = jax.lax.scan(step, tokens, None,
                                   length=self._max_new)
            return news.T  # [B, max_new]

        self._decode = jax.jit(_decode)

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import jax.numpy as jnp
        toks_list = batch[self._in]
        vocab = self._cfg.vocab_size
        max_len = min(self._bucket(max(len(t) for t in toks_list)),
                      self._cfg.max_seq_len)
        padded = np.zeros((len(toks_list), max_len), np.int32)
        for i, t in enumerate(toks_list):
            t = np.asarray(t)[-max_len:] % vocab
            padded[i, max_len - len(t):] = t  # left-pad (decode reads tail)
        news = np.asarray(self._decode(self._params, jnp.asarray(padded)))
        out = dict(batch)
        out[self._out] = [news[i] for i in range(len(toks_list))]
        return out


@dataclass
class ProcessorConfig:
    """Reference: batch/processor config objects."""
    model: Optional[str] = None          # HF id for tokenizer/template
    batch_size: int = 16
    concurrency: int = 1
    max_new_tokens: int = 8
    use_chat_template: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class StageSpec:
    """A stateful stage to be constructed inside pool actors: the class
    plus ctor kwargs ship to each actor, so heavy state (tokenizer,
    model params, jitted decode) is built once per actor instead of being
    re-pickled per block (reference: stages as Ray Data actor-pool UDFs).
    """

    cls: type
    kwargs: Dict[str, Any] = field(default_factory=dict)
    concurrency: int = 1


class Processor:
    """Chains stages over a Dataset (reference: batch/processor.py)."""

    def __init__(self, stages: List[Any], batch_size: int = 16):
        self.stages = list(stages)
        self.batch_size = batch_size

    def __call__(self, dataset):
        for stage in self.stages:
            if isinstance(stage, StageSpec):
                dataset = dataset.map_batches(
                    stage.cls, batch_size=self.batch_size,
                    fn_constructor_kwargs=dict(stage.kwargs),
                    concurrency=stage.concurrency)
            else:
                dataset = dataset.map_batches(
                    stage, batch_size=self.batch_size)
        return dataset


def build_processor(config: ProcessorConfig) -> Processor:
    """Standard pipeline: [chat template] -> tokenize -> generate ->
    detokenize (reference: build_llm_processor). Stateful stages are
    StageSpecs — constructed per pool actor, not on the driver."""
    stages: List[Any] = []
    if config.use_chat_template:
        stages.append(StageSpec(ChatTemplateStage,
                                {"model": config.model}))
    stages.append(StageSpec(TokenizeStage, {"model": config.model}))
    stages.append(StageSpec(
        GPTInferenceStage, {"max_new_tokens": config.max_new_tokens},
        concurrency=config.concurrency))
    stages.append(StageSpec(DetokenizeStage, {"model": config.model}))
    return Processor(stages, batch_size=config.batch_size)
