"""Continuous batching for the native generation engine.

Net-new TPU-native capability (the reference delegates this to vLLM on
its actors): late requests JOIN a running decode batch — a free KV-cache
slot is prefilled while the other slots keep decoding — and slots are
reused the moment a stream finishes (EOS / token budget), so aggregate
decode throughput approaches batch-width tokens per step instead of one
per step per sequential request. Static shapes throughout: one XLA
compile per prompt-length bucket plus one batched decode compile; slot
occupancy changes never trigger recompilation (vLLM-style continuous
batching re-expressed for XLA's compile-once model).

Driven by a single decode thread per engine (a Serve replica owns one
engine; its requests share the batch). Thread-safe submit() returns an
iterator of decoded text pieces.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

_SENTINEL = object()


class _Request:
    __slots__ = ("ids", "max_new", "temperature", "out", "stop_token",
                 "seed")

    def __init__(self, ids, max_new, temperature, stop_token, seed):
        self.ids = ids
        self.max_new = max_new
        self.temperature = temperature
        self.stop_token = stop_token
        self.seed = seed
        self.out: "queue.Queue" = queue.Queue()


class _Slot:
    __slots__ = ("req", "pos", "emitted", "rng", "last_token")

    def __init__(self, req: _Request, pos: int, rng):
        self.req = req
        self.pos = pos          # next decode position (== tokens so far)
        self.emitted = 0
        self.rng = rng
        self.last_token = 0


class ContinuousBatchingEngine:
    """Shared-batch KV-cache decode with slot insertion/reuse."""

    def __init__(self, cfg=None, params=None, tokenizer=None,
                 max_batch: int = 8, max_len: Optional[int] = None,
                 seed: int = 0):
        import jax

        from ..models import GPTConfig, gpt_init
        from ..models.generate import init_cache, make_continuous_fns
        from .serving import ByteTokenizer

        self.tokenizer = tokenizer or ByteTokenizer()
        self.cfg = cfg or GPTConfig(
            vocab_size=max(ByteTokenizer.vocab_size, 272),
            d_model=256, n_heads=8, n_layers=4, d_ff=1024,
            max_seq_len=512)
        self.params = params if params is not None else gpt_init(
            jax.random.PRNGKey(seed), self.cfg)
        self.max_batch = int(max_batch)
        self.max_len = int(max_len or self.cfg.max_seq_len)
        self._prefill, self._decode = make_continuous_fns(
            self.cfg, self.max_len, self.max_batch)
        self._cache = init_cache(self.cfg, self.max_batch, self.max_len)
        self._slots: List[Optional[_Slot]] = [None] * self.max_batch
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # Step counter — tests assert late requests really joined a
        # RUNNING batch (their first token decoded at a step > 0 while
        # another slot was mid-stream).
        self.steps = 0

    # -- public api --------------------------------------------------------
    def submit(self, prompt: str, max_new_tokens: int = 32,
               temperature: float = 0.0,
               stop_token: Optional[int] = None,
               seed: int = 0) -> Iterator[str]:
        """Enqueue a request; returns an iterator of decoded text
        pieces. The request joins the running batch as soon as a slot
        frees (or immediately when one is open)."""
        import codecs

        encoded = self.tokenizer.encode(prompt)
        keep = self.max_len - max(1, min(max_new_tokens, 16))
        if len(encoded) > keep:
            encoded = encoded[-keep:]
        budget = min(max_new_tokens, self.max_len - len(encoded))
        req = _Request(encoded, max(1, budget), float(temperature),
                       stop_token, seed)
        with self._lock:
            if self._closed:
                raise RuntimeError("engine closed")
            self._pending.put(req)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="cb-decode")
                self._thread.start()
        self._wake.set()

        def _stream():
            decoder = codecs.getincrementaldecoder("utf-8")(
                errors="replace")
            while True:
                item = req.out.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, BaseException):
                    raise item
                if 0 <= item < 256:
                    piece = decoder.decode(bytes([item]))
                    if piece:
                        yield piece
            tail = decoder.decode(b"", final=True)
            if tail:
                yield tail
        return _stream()

    def complete(self, prompt: str, max_new_tokens: int = 32,
                 temperature: float = 0.0, **kw) -> str:
        return "".join(self.submit(prompt, max_new_tokens, temperature,
                                   **kw))

    def close(self):
        with self._lock:
            self._closed = True
        self._wake.set()

    # -- decode loop -------------------------------------------------------
    def _admit(self) -> None:
        """Prefill pending requests into free slots (called between
        decode steps — this is the 'late request joins a running
        batch' moment)."""
        import numpy as np

        from ..models.generate import _bucket_len

        for i in range(self.max_batch):
            if self._slots[i] is not None:
                continue
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            true_len = len(req.ids)
            bucket = min(_bucket_len(true_len, self.max_len),
                         self.max_len)
            padded = req.ids + [0] * (bucket - true_len)
            tokens = np.asarray([padded], np.int32)
            try:
                last, self._cache = self._prefill(
                    self.params, tokens, self._cache, i, true_len)
            except BaseException as e:  # noqa: BLE001
                # The request is already popped from _pending and holds
                # no slot: _fail_all can't see it, so a prefill failure
                # (OOM, compile error) must terminate ITS stream here or
                # submit()'s consumer blocks forever on req.out.
                req.out.put(e)
                req.out.put(_SENTINEL)
                raise
            rng = np.random.default_rng(req.seed)
            slot = _Slot(req, true_len, rng)
            self._slots[i] = slot
            self._emit(i, np.asarray(last))

    def _emit(self, i: int, logits) -> None:
        """Sample one token for slot i from host-side logits; push to
        the request's stream; retire the slot at EOS/budget. Host-side
        sampling keeps per-request temperature/seed without burning a
        compile per combination.

        Position bookkeeping mirrors models.generate: slot.pos is where
        the just-sampled token WILL be written by the next decode step
        (== tokens currently in the cache); the loop advances it after
        the decode that consumes the token."""
        import numpy as np

        slot = self._slots[i]
        req = slot.req
        if req.temperature <= 0.0:
            token = int(np.argmax(logits))
        else:
            z = logits.astype(np.float64) / req.temperature
            z -= z.max()
            p = np.exp(z)
            p /= p.sum()
            token = int(slot.rng.choice(len(p), p=p))
        req.out.put(token)
        slot.emitted += 1
        slot.last_token = token
        done = (slot.emitted >= req.max_new
                or (req.stop_token is not None
                    and token == req.stop_token)
                or slot.pos >= self.max_len)
        if done:
            req.out.put(_SENTINEL)
            self._slots[i] = None   # slot free: next _admit reuses it

    def _fail_all(self, exc: Optional[BaseException]) -> None:
        """Terminate every active and pending stream; exc is re-raised
        in consumers when given, else the streams just end."""
        for i, s in enumerate(self._slots):
            if s is not None:
                if exc is not None:
                    s.req.out.put(exc)
                s.req.out.put(_SENTINEL)
                self._slots[i] = None
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                return
            if exc is not None:
                req.out.put(exc)
            req.out.put(_SENTINEL)

    def _loop(self) -> None:
        import numpy as np
        try:
            while True:
                self._admit()
                active = [i for i in range(self.max_batch)
                          if self._slots[i] is not None]
                if not active:
                    with self._lock:
                        if self._closed:
                            # Atomic with submit()'s check+enqueue:
                            # drain anything that raced in so no
                            # consumer blocks forever.
                            self._fail_all(
                                RuntimeError("engine closed"))
                            return
                    self._wake.wait(timeout=0.5)
                    self._wake.clear()
                    continue
                tokens = np.zeros(self.max_batch, np.int32)
                pos = np.zeros(self.max_batch, np.int32)
                for i in active:
                    slot = self._slots[i]
                    tokens[i] = slot.last_token
                    pos[i] = slot.pos  # where this token is written
                logits, self._cache = self._decode(
                    self.params, tokens, pos, self._cache)
                self.steps += 1
                logits_np = np.asarray(logits)
                for i in active:
                    slot = self._slots[i]
                    if slot is not None:
                        slot.pos += 1  # the decode wrote at old pos
                        self._emit(i, logits_np[i])
        except BaseException as e:  # noqa: BLE001
            # The engine is dead: close it so later submit() raises
            # instead of enqueueing into a loop that no longer runs,
            # and fail EVERY stream — active and still-pending — with
            # the error (a pending request ending silently would look
            # like an empty completion).
            with self._lock:
                self._closed = True
                self._fail_all(e)
