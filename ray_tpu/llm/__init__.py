"""ray_tpu.llm — batch LLM inference pipelines.

Reference parity: python/ray/llm/_internal/batch/ — a Processor chains
stages (chat template -> tokenize -> inference -> detokenize,
stages/chat_template_stage.py, tokenize_stage.py, http_request_stage.py)
over Ray Data. Here stages run over ray_tpu.data Datasets via
map_batches; the inference stage is TPU-native: a jitted greedy-decode
loop over the in-repo GPT model on TPU actors (`num_tpus=1` actor pool),
with power-of-two padding so XLA compiles a few bucket shapes
(reference has no engine in-tree either — llm/ is the pipeline layer).
"""
from .batch import (ChatTemplateStage, DetokenizeStage, GPTInferenceStage,
                    HttpRequestStage, Processor, ProcessorConfig,
                    TokenizeStage, build_processor)
from .continuous import ContinuousBatchingEngine
from .serving import ByteTokenizer, LLMEngine, build_llm_app

__all__ = ["ByteTokenizer", "ChatTemplateStage",
           "ContinuousBatchingEngine", "DetokenizeStage",
           "GPTInferenceStage", "HttpRequestStage", "LLMEngine",
           "Processor", "ProcessorConfig", "TokenizeStage",
           "build_llm_app", "build_processor"]
