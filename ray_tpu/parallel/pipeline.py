"""Pipeline parallelism over the `pp` mesh axis — forward AND training.

Net-new vs the reference (SURVEY.md §2.4: PP "Not in-tree", built by users
from ADAG multi-actor pipelines): here a pipeline is a compiled SPMD
program — stage parameters are sharded over `pp`, microbatches flow
stage-to-stage via `lax.ppermute`, and the whole GPipe schedule is a
`lax.scan` inside one jit (the XLA analogue of a CompiledDAG of actors,
dag/compiled_dag_node.py:767, with ICI hops instead of NCCL p2p channels).

Backward: the schedule is differentiable end to end, and reverse-mode AD
of the scan IS the backward pipeline — the transpose of each forward
``ppermute`` hop is the reverse hop, so gradients flow last-stage ->
first-stage in reverse tick order (a GPipe backward schedule), with the
scan's saved carries as the per-tick activation stash. Grads of the
stacked stage parameters come back sharded over `pp` exactly like the
parameters themselves. `make_pipelined_train_fn` packages this as a
(loss, grads) step; tests verify grads match a single-device sequential
model bit-for-bit (tests/test_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   axis_name: str = "pp", n_microbatches: int = None):
    """Run a GPipe pipeline; call INSIDE shard_map over `axis_name`.

    stage_fn(params, activations) -> activations, applied by every rank to
    its own stage. `x`: this rank's microbatch stack
    [n_micro_local, ...batch...] — the global batch is split over
    microbatches, each microbatch visits every stage in ring order.

    Schedule: n_micro + n_stages - 1 ticks. At tick t, stage s processes
    microbatch (t - s) when 0 <= t - s < n_micro. Activations hop
    stage->stage+1 between ticks via ppermute; outputs complete at the
    last stage and are rotated back to stage 0's slot for collection.
    """
    n_stages = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    n_micro = x.shape[0]
    total_ticks = n_micro + n_stages - 1

    from .ops import pvary
    state = jnp.zeros_like(x[0])          # current activation on this rank
    outputs = jnp.zeros_like(x)           # completed microbatches
    state, outputs = pvary((state, outputs), axis_name)

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (if any remain); other stages use
        # the activation that just hopped in.
        feed = x[jnp.minimum(t, n_micro - 1)]
        state = jnp.where(rank == 0,
                          jnp.where(t < n_micro, feed, state), state)
        mb_idx = t - rank                 # microbatch this stage holds
        active = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        new_state = stage_fn(stage_params, state)
        state = jnp.where(active, new_state, state)
        # Last stage completes microbatch mb_idx.
        is_done = jnp.logical_and(active, rank == n_stages - 1)
        outputs = jnp.where(
            is_done,
            lax.dynamic_update_index_in_dim(
                outputs, state, jnp.maximum(mb_idx, 0), 0),
            outputs)
        # Hop activations forward around the ring.
        state = lax.ppermute(state, axis_name, fwd_perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(total_ticks))
    # Completed outputs live on the last stage; broadcast to all ranks so
    # the caller sees replicated results (psum over one-hot contribution).
    contrib = jnp.where(rank == n_stages - 1, outputs,
                        jnp.zeros_like(outputs))
    return lax.psum(contrib, axis_name)


def _pipeline_forward(mesh, stage_fn: Callable, n_microbatches: int,
                      axis_name: str, params_spec, x_spec):
    """Shared shard_map builder: stage_params stacked on axis 0 (one
    slice per stage, sharded over `axis_name`); x global
    [n_micro * mb_size, ...]."""
    from ray_tpu.parallel.ops import shard_map
    from jax.sharding import PartitionSpec as P

    params_spec = params_spec if params_spec is not None else P(axis_name)
    x_spec = x_spec if x_spec is not None else P()

    def local_fn(stage_params, x):
        # stage_params arrive with a leading stage axis of length 1.
        own = jax.tree.map(lambda p: p[0], stage_params)
        xm = x.reshape((n_microbatches, -1) + x.shape[1:])
        out = pipeline_apply(stage_fn, own, xm, axis_name)
        return out.reshape((-1,) + out.shape[2:])

    return shard_map(local_fn, mesh=mesh,
                     in_specs=(params_spec, x_spec),
                     out_specs=x_spec)


def make_pipelined_fn(mesh, stage_fn: Callable, n_microbatches: int,
                      axis_name: str = "pp",
                      params_spec=None, x_spec=None):
    """jit'd GPipe forward (see _pipeline_forward)."""
    return jax.jit(_pipeline_forward(mesh, stage_fn, n_microbatches,
                                     axis_name, params_spec, x_spec))


def make_pipelined_train_fn(mesh, stage_fn: Callable, loss_fn: Callable,
                            n_microbatches: int, axis_name: str = "pp",
                            params_spec=None, x_spec=None):
    """Training step over a GPipe pipeline: returns a jitted
    ``step(stage_params, x, y) -> (loss, grads)`` where `stage_params`
    are stacked on axis 0 (one slice per stage, sharded over `axis_name`)
    and `grads` come back with the same sharding.

    loss_fn(outputs, y) -> scalar, applied to the full pipeline output
    (all microbatches re-concatenated). The backward runs as the
    reverse-tick pipeline (see module docstring).
    """
    apply = _pipeline_forward(mesh, stage_fn, n_microbatches,
                              axis_name, params_spec, x_spec)

    def loss_of(stage_params, x, y):
        return loss_fn(apply(stage_params, x), y)

    return jax.jit(jax.value_and_grad(loss_of))
