"""Pipeline parallelism over the `pp` mesh axis — forward AND training.

Net-new vs the reference (SURVEY.md §2.4: PP "Not in-tree", built by users
from ADAG multi-actor pipelines): here a pipeline is a compiled SPMD
program — stage parameters are sharded over `pp`, microbatches flow
stage-to-stage via `lax.ppermute`, and the whole GPipe schedule is a
`lax.scan` inside one jit (the XLA analogue of a CompiledDAG of actors,
dag/compiled_dag_node.py:767, with ICI hops instead of NCCL p2p channels).

Backward: the schedule is differentiable end to end, and reverse-mode AD
of the scan IS the backward pipeline — the transpose of each forward
``ppermute`` hop is the reverse hop, so gradients flow last-stage ->
first-stage in reverse tick order (a GPipe backward schedule), with the
scan's saved carries as the per-tick activation stash. Grads of the
stacked stage parameters come back sharded over `pp` exactly like the
parameters themselves. `make_pipelined_train_fn` packages this as a
(loss, grads) step; tests verify grads match a single-device sequential
model bit-for-bit (tests/test_parallel.py).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   axis_name: str = "pp", n_microbatches: int = None):
    """Run a GPipe pipeline; call INSIDE shard_map over `axis_name`.

    stage_fn(params, activations) -> activations, applied by every rank to
    its own stage. `x`: this rank's microbatch stack
    [n_micro_local, ...batch...] — the global batch is split over
    microbatches, each microbatch visits every stage in ring order.

    Schedule: n_micro + n_stages - 1 ticks. At tick t, stage s processes
    microbatch (t - s) when 0 <= t - s < n_micro. Activations hop
    stage->stage+1 between ticks via ppermute; outputs complete at the
    last stage and are rotated back to stage 0's slot for collection.
    """
    n_stages = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    n_micro = x.shape[0]
    total_ticks = n_micro + n_stages - 1

    from .ops import pvary
    state = jnp.zeros_like(x[0])          # current activation on this rank
    outputs = jnp.zeros_like(x)           # completed microbatches
    state, outputs = pvary((state, outputs), axis_name)

    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (if any remain); other stages use
        # the activation that just hopped in.
        feed = x[jnp.minimum(t, n_micro - 1)]
        state = jnp.where(rank == 0,
                          jnp.where(t < n_micro, feed, state), state)
        mb_idx = t - rank                 # microbatch this stage holds
        active = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        new_state = stage_fn(stage_params, state)
        state = jnp.where(active, new_state, state)
        # Last stage completes microbatch mb_idx.
        is_done = jnp.logical_and(active, rank == n_stages - 1)
        outputs = jnp.where(
            is_done,
            lax.dynamic_update_index_in_dim(
                outputs, state, jnp.maximum(mb_idx, 0), 0),
            outputs)
        # Hop activations forward around the ring.
        state = lax.ppermute(state, axis_name, fwd_perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(total_ticks))
    # Completed outputs live on the last stage; broadcast to all ranks so
    # the caller sees replicated results (psum over one-hot contribution).
    contrib = jnp.where(rank == n_stages - 1, outputs,
                        jnp.zeros_like(outputs))
    return lax.psum(contrib, axis_name)


def _pipeline_forward(mesh, stage_fn: Callable, n_microbatches: int,
                      axis_name: str, params_spec, x_spec):
    """Shared shard_map builder: stage_params stacked on axis 0 (one
    slice per stage, sharded over `axis_name`); x global
    [n_micro * mb_size, ...]."""
    from ray_tpu.parallel.ops import shard_map
    from jax.sharding import PartitionSpec as P

    params_spec = params_spec if params_spec is not None else P(axis_name)
    x_spec = x_spec if x_spec is not None else P()

    def local_fn(stage_params, x):
        # stage_params arrive with a leading stage axis of length 1.
        own = jax.tree.map(lambda p: p[0], stage_params)
        xm = x.reshape((n_microbatches, -1) + x.shape[1:])
        out = pipeline_apply(stage_fn, own, xm, axis_name)
        return out.reshape((-1,) + out.shape[2:])

    return shard_map(local_fn, mesh=mesh,
                     in_specs=(params_spec, x_spec),
                     out_specs=x_spec)


def make_pipelined_fn(mesh, stage_fn: Callable, n_microbatches: int,
                      axis_name: str = "pp",
                      params_spec=None, x_spec=None):
    """jit'd GPipe forward (see _pipeline_forward)."""
    return jax.jit(_pipeline_forward(mesh, stage_fn, n_microbatches,
                                     axis_name, params_spec, x_spec))


def one_f1b_schedule(n_stages: int, n_micro: int):
    """Static 1F1B tick table (reference for the schedule shape:
    Megatron-LM's non-interleaved 1F1B; the reference framework's users
    build this from ADAG actor pipelines, dag/compiled_dag_node.py:767).

    Simulated at trace time: each tick every stage runs one of
    idle(0)/forward(1)/backward(2) on a microbatch. Policy: stage s
    keeps at most (n_stages - s) microbatches in flight — warmup
    forwards, steady 1F1B alternation, cooldown backwards — which is
    what bounds the activation stash by pipeline depth instead of
    microbatch count.

    Returns (action[T, S], mb[T, S]) numpy int32 arrays.
    """
    import numpy as np

    S, M = n_stages, n_micro
    f_done = [[-1] * M for _ in range(S)]   # tick F(s,m) completed
    b_done = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    next_b = [0] * S
    actions, mbs = [], []
    t = 0
    while any(nb < M for nb in next_b):
        act_row = [0] * S
        mb_row = [0] * S
        for s in range(S):
            m_f, m_b = next_f[s], next_b[s]
            f_ready = m_f < M and (
                s == 0 or (f_done[s - 1][m_f] >= 0
                           and f_done[s - 1][m_f] < t))
            b_ready = m_b < M and f_done[s][m_b] >= 0 and (
                s == S - 1 or (b_done[s + 1][m_b] >= 0
                               and b_done[s + 1][m_b] < t))
            in_flight = m_f - m_b
            cap = S - s
            # 1F1B: forward only while under the in-flight cap (the
            # memory bound); at the cap, drain a backward (or wait).
            do_b = b_ready and (in_flight >= cap or not f_ready)
            do_f = (not do_b) and f_ready and in_flight < cap
            if do_b:
                act_row[s], mb_row[s] = 2, m_b
                b_done[s][m_b] = t
                next_b[s] += 1
            elif do_f:
                act_row[s], mb_row[s] = 1, m_f
                f_done[s][m_f] = t
                next_f[s] += 1
        actions.append(act_row)
        mbs.append(mb_row)
        t += 1
        if t > 4 * (M + S) + 8:  # defensive: schedule must terminate
            raise RuntimeError("1F1B schedule did not converge")
    return (np.asarray(actions, dtype=np.int32),
            np.asarray(mbs, dtype=np.int32))


def make_1f1b_train_fn(mesh, stage_fn: Callable, loss_fn: Callable,
                       n_microbatches: int, axis_name: str = "pp",
                       params_spec=None, x_spec=None):
    """Training step over a 1F1B pipeline schedule: like
    make_pipelined_train_fn but with the backward INSIDE the schedule —
    per-stage activation stash bounded by pipeline depth (not microbatch
    count), and the stage backward recomputes the stage forward from the
    saved INPUT (Megatron-style activation recompute), so per-tick
    residuals never accumulate across ticks.

    Returns jitted ``step(stage_params, x, y) -> (loss, grads)`` with
    the same contract as make_pipelined_train_fn.
    """
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.ops import shard_map

    params_spec = params_spec if params_spec is not None else P(axis_name)
    x_spec = x_spec if x_spec is not None else P()
    n_stages = mesh.shape[axis_name]
    action_tbl, mb_tbl = one_f1b_schedule(n_stages, n_microbatches)

    def local_fn(stage_params, x, y):
        own = jax.tree.map(lambda p: p[0], stage_params)
        xm = x.reshape((n_microbatches, -1) + x.shape[1:])
        ym = y.reshape((n_microbatches, -1) + y.shape[1:])
        rank = lax.axis_index(axis_name)
        S = n_stages
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]
        act_t = jnp.asarray(action_tbl)
        mb_t = jnp.asarray(mb_tbl)
        mb_shape = xm.shape[1:]

        from .ops import pvary
        # Stash of stage INPUTS, ring-indexed mb % S — the 1F1B memory
        # bound. grad ring buffers out-of-order backward arrivals.
        stash = jnp.zeros((S,) + mb_shape, xm.dtype)
        grads_in = jnp.zeros((S,) + mb_shape, xm.dtype)
        dparams = jax.tree.map(jnp.zeros_like, own)
        loss_acc = jnp.zeros((), jnp.float32)
        carry0 = pvary((stash, grads_in, dparams, loss_acc), axis_name)

        is_first = rank == 0
        is_last = rank == S - 1

        def stage_loss(params, a_in, y_mb):
            out = stage_fn(params, a_in)
            return loss_fn(out, y_mb)

        def tick(carry, t):
            stash, grads_in, dparams, loss_acc = carry
            action = act_t[t, rank]
            m = mb_t[t, rank]
            slot = m % S

            def do_idle(stash, grads_in, dparams, loss_acc):
                z = jnp.zeros(mb_shape, xm.dtype)
                return pvary((stash, grads_in, dparams, loss_acc,
                              z, jnp.int32(0), z, jnp.int32(0)),
                             axis_name)

            def do_fwd(stash, grads_in, dparams, loss_acc):
                a_in = jnp.where(is_first, xm[m], stash[slot])
                # Stage 0's saved input is its x microbatch (uniform
                # stash so the backward recompute reads one place).
                stash = lax.dynamic_update_index_in_dim(
                    stash, a_in, slot, 0)
                out = stage_fn(own, a_in)
                return pvary((stash, grads_in, dparams, loss_acc,
                              out, jnp.int32(1),
                              jnp.zeros(mb_shape, xm.dtype),
                              jnp.int32(0)), axis_name)

            def do_bwd(stash, grads_in, dparams, loss_acc):
                a_in = stash[slot]

                def last_branch(_):
                    (lval, (dp, da)) = jax.value_and_grad(
                        stage_loss, argnums=(0, 1))(own, a_in, ym[m])
                    # Both cond branches must carry identical
                    # varying-manual-axes types.
                    return pvary((lval, dp, da), axis_name)

                def mid_branch(_):
                    _out, vjp = jax.vjp(stage_fn, own, a_in)
                    dp, da = vjp(grads_in[slot])
                    return pvary((jnp.zeros((), jnp.float32), dp, da),
                                 axis_name)

                lval, dp, da = lax.cond(is_last, last_branch,
                                        mid_branch, None)
                dparams = jax.tree.map(jnp.add, dparams, dp)
                loss_acc = loss_acc + lval
                return pvary((stash, grads_in, dparams, loss_acc,
                              jnp.zeros(mb_shape, xm.dtype), jnp.int32(0),
                              da.astype(xm.dtype), jnp.int32(1)),
                             axis_name)

            (stash, grads_in, dparams, loss_acc,
             f_msg, f_valid, b_msg, b_valid) = lax.switch(
                action, [do_idle, do_fwd, do_bwd],
                stash, grads_in, dparams, loss_acc)

            # Hop messages every tick: F outputs ride forward, input
            # grads ride backward; receivers file them by microbatch.
            f_rx = lax.ppermute((f_msg, f_valid, m), axis_name, fwd_perm)
            b_rx = lax.ppermute((b_msg, b_valid, m), axis_name, bwd_perm)
            rx_act, rx_fv, rx_fm = f_rx
            rx_grad, rx_bv, rx_bm = b_rx
            # The rings wrap: stage S-1's F output lands on stage 0 and
            # stage 0's input grad lands on stage S-1. Neither is a real
            # message — storing them would CORRUPT a live stash slot of
            # the same residue class.
            rx_fv = jnp.where(is_first, 0, rx_fv)
            rx_bv = jnp.where(is_last, 0, rx_bv)
            stash = jnp.where(
                rx_fv > 0,
                lax.dynamic_update_index_in_dim(
                    stash, rx_act, rx_fm % S, 0),
                stash)
            grads_in = jnp.where(
                rx_bv > 0,
                lax.dynamic_update_index_in_dim(
                    grads_in, rx_grad, rx_bm % S, 0),
                grads_in)
            return (stash, grads_in, dparams, loss_acc), None

        (stash, grads_in, dparams, loss_acc), _ = lax.scan(
            tick, carry0, jnp.arange(action_tbl.shape[0]))
        # Per-mb losses live on the last stage; grads are per-stage.
        # Both are SUMS over microbatches of per-mb means — divide by M
        # so loss/grads equal the full-batch mean formulation.
        loss = lax.psum(jnp.where(is_last, loss_acc, 0.0),
                        axis_name) / n_microbatches
        grads = jax.tree.map(lambda g: g[None] / n_microbatches, dparams)
        return loss, grads

    fn = shard_map(local_fn, mesh=mesh,
                   in_specs=(params_spec, x_spec, x_spec),
                   out_specs=(P(), params_spec))
    return jax.jit(fn)


def make_pipelined_train_fn(mesh, stage_fn: Callable, loss_fn: Callable,
                            n_microbatches: int, axis_name: str = "pp",
                            params_spec=None, x_spec=None):
    """Training step over a GPipe pipeline: returns a jitted
    ``step(stage_params, x, y) -> (loss, grads)`` where `stage_params`
    are stacked on axis 0 (one slice per stage, sharded over `axis_name`)
    and `grads` come back with the same sharding.

    loss_fn(outputs, y) -> scalar, applied to the full pipeline output
    (all microbatches re-concatenated). The backward runs as the
    reverse-tick pipeline (see module docstring).
    """
    apply = _pipeline_forward(mesh, stage_fn, n_microbatches,
                              axis_name, params_spec, x_spec)

    def loss_of(stage_params, x, y):
        return loss_fn(apply(stage_params, x), y)

    return jax.jit(jax.value_and_grad(loss_of))
