"""Sequence/context parallelism: ring attention over an ICI ring.

Net-new vs the reference (SURVEY.md §2.4: SP/CP "Absent — must be built
natively"): causal ring attention — each device holds a sequence shard of
Q/K/V; K/V blocks rotate around the `sp` mesh axis via `lax.ppermute`
while each device accumulates blockwise attention with a running online
softmax, so peak memory is O(S_local²) and the KV transfers overlap with
block compute on the ICI ring. Ulysses-style all-to-all head/sequence
re-sharding is provided as the alternative strategy.

Use inside shard_map (see `sequence_parallel_attention` for the wrapper).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import DEFAULT_MASK_VALUE


from .ops import pvary as _pvary


def _block_attn(q, k, v, scale, mask):
    """One blockwise attention contribution with stable statistics.

    Returns (unnormalized_out fp32, row_max fp32, row_sumexp fp32).
    q: [B,H,Sq,D], k/v: [B,H,Sk,D], mask broadcastable [Sq,Sk] bool.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1)                          # [B,H,Sq]
    # Fully-masked rows: keep exp() finite.
    m_safe = jnp.maximum(m, DEFAULT_MASK_VALUE / 2)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v
                     ).astype(jnp.float32)
    return out, m_safe, l


def ring_attention(q, k, v, axis_name: str = "sp",
                   sm_scale: Optional[float] = None):
    """Causal ring attention; call INSIDE shard_map over `axis_name`.

    q/k/v: local sequence shards [B, H, S_local, D]; global sequence is the
    concatenation over the axis in rank order. Returns [B, H, S_local, D].

    Overlap: the ring is unrolled (the axis size is static), and each
    step's ``ppermute`` for the NEXT K/V block is emitted BEFORE the
    current block's attention compute — the transfer has no data
    dependence on the block math, so XLA's latency-hiding scheduler runs
    the collective-permute-start/done pair concurrently with the einsums
    (double buffering; the last step sends nothing). Memory: each block
    step is rematerialized (``jax.checkpoint``), so the backward
    recomputes per-block probabilities instead of storing [Sq, Sk]
    matrices per step.
    """
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s_local = q.shape[-2]

    qpos = jax.lax.broadcasted_iota(jnp.int32, (s_local, s_local), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (s_local, s_local), 1)
    tri_mask = qpos >= kpos                 # within-shard causal
    full_mask = jnp.ones((s_local, s_local), dtype=bool)
    zero_mask = jnp.zeros((s_local, s_local), dtype=bool)

    # Rotate K/V around the ring: after t steps, we hold the block that
    # originated at rank (rank - t) mod n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    @functools.partial(jax.checkpoint, static_argnums=())
    def block_step(t_src_is_self, t_src_is_left, q, kt, vt, acc, m, l):
        # src < rank: fully visible. src == rank: causal. src > rank: none.
        mask = jnp.where(t_src_is_left, full_mask,
                         jnp.where(t_src_is_self, tri_mask, zero_mask))
        out_b, m_b, l_b = _block_attn(q, kt, vt, scale, mask)
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_b - m_new)
        acc = acc * alpha[..., None] + out_b * beta[..., None]
        l = l * alpha + l_b * beta
        return acc, m_new, l

    b, h, _, d = q.shape
    acc = jnp.zeros((b, h, s_local, d), dtype=jnp.float32)
    m = jnp.full((b, h, s_local), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((b, h, s_local), dtype=jnp.float32)
    acc, m, l = _pvary((acc, m, l), axis_name)
    kt, vt = k, v
    for t in range(n):
        if t + 1 < n:
            # Next hop FIRST: independent of this block's compute, so the
            # scheduler overlaps the ICI transfer with the einsums below.
            kt_next = lax.ppermute(kt, axis_name, perm)
            vt_next = lax.ppermute(vt, axis_name, perm)
        src = (rank - t) % n
        acc, m, l = block_step(src == rank, src < rank,
                               q, kt, vt, acc, m, l)
        if t + 1 < n:
            kt, vt = kt_next, vt_next
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)


def sequence_parallel_attention(mesh, q, k, v, axis_name: str = "sp",
                                sm_scale: Optional[float] = None):
    """shard_map wrapper: q/k/v are global [B, H, S, D] arrays (sharded or
    not); the sequence axis is split over `axis_name` and ring attention
    runs on the shards."""
    from ray_tpu.parallel.ops import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(q, k, v, axis_name: str = "sp",
                      sm_scale: Optional[float] = None,
                      attn_fn=None):
    """Ulysses/DeepSpeed-style sequence parallelism; call INSIDE shard_map.

    all_to_all swaps the sharded axis from sequence to heads, computes full
    (local) attention per head group, and swaps back. Requires
    n_heads % axis_size == 0. q/k/v: [B, H, S_local, D].
    """
    from ..ops.attention import mha_reference

    attn = attn_fn or mha_reference
    # [B, H, S/n, D] -> [B, H/n, S, D]
    q = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                       tiled=True)
    out = attn(q, k, v, True, sm_scale)
    # back: [B, H/n, S, D] -> [B, H, S/n, D]
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
