"""Mixture-of-Experts with expert parallelism over the `ep` mesh axis.

Net-new vs the reference (SURVEY.md §2.4: EP "Absent"): top-k token
routing with capacity-bounded dense dispatch — einsum-based combine/
dispatch (compiler-friendly, no dynamic shapes) and `lax.all_to_all`
shuffles across the expert axis when experts are sharded.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def top2_gating(logits, capacity: int):
    """Top-2 gating with capacity dropping (Switch/GShard style).

    logits: [tokens, experts]. Returns (dispatch [T, E, C] bool-ish,
    combine [T, E, C] float, aux_loss scalar).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    def one_route(p, mask_prev, offset):
        idx = jnp.argmax(jnp.where(mask_prev, -jnp.inf, p), axis=-1)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        # 1-based position of each token within its expert's queue,
        # continuing after `offset` slots already taken by earlier routes
        # (GShard: second-choice positions start after all first choices).
        pos = (jnp.cumsum(onehot, axis=0) + offset[None, :]) * onehot
        keep = (pos > 0) & (pos <= capacity)
        pos0 = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
        return idx, onehot, keep, pos0

    zero_off = jnp.zeros((e,), dtype=jnp.float32)
    idx1, oh1, keep1, pos1 = one_route(
        probs, jnp.zeros_like(probs, dtype=bool), zero_off)
    mask1 = oh1.astype(bool)
    count1 = jnp.sum(oh1, axis=0)
    idx2, oh2, keep2, pos2 = one_route(probs, mask1, count1)

    g1 = jnp.sum(probs * oh1, axis=-1)
    g2 = jnp.sum(probs * oh2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    cap_oh = functools.partial(jax.nn.one_hot, num_classes=capacity,
                               dtype=jnp.float32)
    # [T, E, C] dispatch/combine tensors
    d1 = oh1[:, :, None] * cap_oh(jnp.sum(pos1 * oh1.astype(jnp.int32),
                                          axis=-1))[:, None, :]
    d2 = oh2[:, :, None] * cap_oh(jnp.sum(pos2 * oh2.astype(jnp.int32),
                                          axis=-1))[:, None, :]
    keep1f = jnp.sum(keep1 * oh1.astype(bool), axis=-1,
                     keepdims=True)[:, :, None]
    keep2f = jnp.sum(keep2 * oh2.astype(bool), axis=-1,
                     keepdims=True)[:, :, None]
    combine = (d1 * g1[:, None, None] * keep1f
               + d2 * g2[:, None, None] * keep2f)
    dispatch = combine > 0
    # load-balancing aux loss (GShard eq. 4)
    density = jnp.mean(oh1, axis=0)
    density_probs = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_probs) * (e ** 2) / e
    return dispatch, combine, aux


def moe_layer(x, gate_w, expert_w1, expert_w2,
              capacity_factor: float = 1.25,
              axis_name: Optional[str] = None):
    """Top-2 MoE FFN. x: [tokens, d]; gate_w: [d, E];
    expert_w1: [E, d, f]; expert_w2: [E, f, d].

    With `axis_name`, call INSIDE shard_map with expert tensors sharded on
    the expert axis: tokens are all_to_all'ed to their experts' shards and
    back (the `ragged_all_to_all`-style dispatch, SURVEY.md §2.4 EP row).
    Without, experts compute locally (einsum over E).
    """
    t, d = x.shape
    e = gate_w.shape[-1]
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)

    if axis_name is None:
        capacity = max(1, int(capacity_factor * t * 2 / e))
        dispatch, combine, aux = top2_gating(logits, capacity)
        # [E, C, d] expert inputs
        xe = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                        dispatch.astype(jnp.float32))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe,
                                   expert_w1.astype(jnp.float32)))
        ye = jnp.einsum("ecf,efd->ecd", h, expert_w2.astype(jnp.float32))
        y = jnp.einsum("ecd,tec->td", ye, combine)
        return y.astype(x.dtype), aux

    # Expert-parallel path: this shard owns e_local = E / n experts and a
    # token shard; tokens travel to their experts' shards and back.
    n = lax.axis_size(axis_name)
    e_local = expert_w1.shape[0]
    capacity = max(1, int(capacity_factor * t * 2 / e))
    dispatch, combine, aux = top2_gating(logits, capacity)
    # Per-expert input buffers built from MY tokens: [E, C, d], grouped by
    # destination shard -> [n_dest, e_local, C, d].
    xe = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                    dispatch.astype(jnp.float32))
    xe = xe.reshape(n, e_local, capacity, d)
    # all_to_all: recv[src, i] = tokens from shard `src` for my expert i.
    recv = lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=0)
    # Fold sources into the capacity axis: [e_local, n*C, d].
    xin = recv.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, d)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin,
                               expert_w1.astype(jnp.float32)))
    ye = jnp.einsum("ecf,efd->ecd", h, expert_w2.astype(jnp.float32))
    # Route outputs back to their source shards.
    back = ye.reshape(e_local, n, capacity, d).transpose(1, 0, 2, 3)
    got = lax.all_to_all(back, axis_name, split_axis=0, concat_axis=0)
    # got[j, i] = my tokens' outputs from expert (j * e_local + i):
    # reassemble the global expert axis in that order -> [E, C, d].
    ye_all = got.reshape(e, capacity, d)
    y = jnp.einsum("ecd,tec->td", ye_all, combine)
    aux = lax.pmean(aux, axis_name)
    return y.astype(x.dtype), aux
