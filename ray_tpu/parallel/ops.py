"""In-jit collective ops: the ICI hot path.

Counterpart of ray_tpu.util.collective for code already inside
jit/shard_map: thin, named wrappers over jax.lax collectives so user code
reads like the reference's `col.allreduce(...)` while compiling to ICI
collectives (SURVEY.md §2.3 TPU-native equivalent column).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

AxisName = Union[str, Sequence[str]]


def shard_map(f, *, mesh=None, in_specs=None, out_specs=None, **kw):
    """Compat shim: jax.shard_map (new home, keyword-only) with fallback
    to jax.experimental.shard_map on older jax. All ray_tpu call sites
    route through here so the deprecated import lives in one place."""
    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pvary(tree, axis_name):
    """Mark values as device-varying over `axis_name` for shard_map's
    varying-manual-axes type system (no-op on jax versions without it).
    Needed on scan/fori_loop carries initialized from constants.

    jax is renaming lax.pvary -> lax.pcast(..., to='varying') (the old
    name warns on recent jax); prefer the new spelling when present."""
    import jax
    from jax import lax
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        def fn(x):
            try:
                return pcast(x, (axis_name,), to="varying")
            except ValueError as e:
                # Only the already-varying case is benign (pvary was
                # idempotent); other ValueErrors must surface here, not
                # as confusing type mismatches deep inside shard_map.
                if "varying" in str(e):
                    return x
                raise
    elif hasattr(lax, "pvary"):
        fn = lambda x: lax.pvary(x, (axis_name,))  # noqa: E731
    else:
        return tree
    try:
        return jax.tree.map(fn, tree)
    except AttributeError:
        return tree


def allreduce(x, axis_name: AxisName = "dp"):
    """Sum across an axis (lax.psum == NCCL allreduce over ICI)."""
    from jax import lax
    return lax.psum(x, axis_name)


def allreduce_mean(x, axis_name: AxisName = "dp"):
    from jax import lax
    return lax.pmean(x, axis_name)


def allgather(x, axis_name: AxisName = "sp", axis: int = 0,
              tiled: bool = True):
    from jax import lax
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name: AxisName = "fsdp", scatter_axis: int = 0):
    """psum_scatter == NCCL reduce-scatter (ZeRO gradient sharding)."""
    from jax import lax
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                            tiled=True)


def all_to_all(x, axis_name: AxisName = "ep", split_axis: int = 0,
               concat_axis: int = 0, tiled: bool = True):
    """MoE dispatch / Ulysses head-sequence swap."""
    from jax import lax
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name: AxisName, perm):
    """Neighbour exchange (ring attention KV rotation, pipeline hops)."""
    from jax import lax
    return lax.ppermute(x, axis_name, perm=perm)


def ring_shift(x, axis_name: AxisName, shift: int = 1,
               axis_size: Optional[int] = None):
    """Rotate values around a ring axis by `shift` (helper over ppermute)."""
    from jax import lax
    n = axis_size if axis_size is not None else lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def axis_index(axis_name: AxisName):
    from jax import lax
    return lax.axis_index(axis_name)


def axis_size(axis_name: AxisName) -> int:
    from jax import lax
    return lax.axis_size(axis_name)


def broadcast_from(x, axis_name: AxisName, src: int = 0):
    """Select src's value on all members of the axis."""
    import jax.numpy as jnp
    from jax import lax
    full = lax.all_gather(x, axis_name, axis=0, tiled=False)
    return jnp.take(full, src, axis=0)
