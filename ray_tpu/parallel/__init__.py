"""ray_tpu.parallel: first-class mesh parallelism strategies.

This layer is where the framework *exceeds* the reference (SURVEY.md §2.4):
the reference ships only data-parallel in-tree and leaves TP/PP/SP/EP to
external libraries over placement groups + NCCL; here they are native mesh
strategies over jax.sharding + shard_map:

* mesh.py       — MeshConfig/make_mesh: dp/fsdp/tp/pp/sp/ep axes over a
                  TPU slice (or a forced-CPU test mesh).
* ops.py        — in-jit collective ops (lax.psum et al.) — the ICI hot
                  path counterpart of ray_tpu.util.collective.
* partition.py  — logical-axis partition rules (Megatron-style TP,
                  ZeRO/FSDP param sharding).
* pipeline.py   — pipeline parallelism via shard_map + ppermute.
* sequence.py   — sequence/context parallelism (ring attention driver).
"""

from .mesh import (  # noqa: F401
    MeshConfig,
    best_mesh_shape,
    make_mesh,
    make_multislice_mesh,
    slice_count,
)
from .partition import (  # noqa: F401
    PartitionRules,
    dcn_rules,
    fsdp_rules,
    logical_to_mesh_axes,
    tp_rules,
)
