"""Partition rules: logical array axes -> mesh axes.

TPU-native equivalent of what the reference leaves to external libraries
(Megatron/DeepSpeed over placement groups, SURVEY.md §2.4 TP/FSDP rows):
parameters carry *logical* axis names ("embed", "mlp", "heads", "kv", ...)
and a rule table maps them to mesh axes, yielding
jax.sharding.PartitionSpecs. Swapping rule tables re-shards the same model
(pure TP, FSDP, or combined) without touching model code — the
compiler-friendly analogue of wrapping modules in DDP/FSDP classes.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

MeshAxis = Union[None, str, Tuple[str, ...]]


class PartitionRules:
    """Ordered (logical_axis -> mesh_axis) table."""

    def __init__(self, rules: Sequence[Tuple[str, MeshAxis]]):
        self._rules: Dict[str, MeshAxis] = dict(rules)

    def mesh_axis(self, logical: Optional[str]) -> MeshAxis:
        if logical is None:
            return None
        return self._rules.get(logical)

    def spec(self, logical_axes: Sequence[Optional[str]]):
        """PartitionSpec for an array annotated with logical axis names."""
        from jax.sharding import PartitionSpec
        return PartitionSpec(
            *[self.mesh_axis(a) for a in logical_axes])

    def with_overrides(self, overrides: Sequence[Tuple[str, MeshAxis]]
                       ) -> "PartitionRules":
        merged = dict(self._rules)
        merged.update(dict(overrides))
        return PartitionRules(list(merged.items()))

    def items(self):
        return self._rules.items()


def tp_rules() -> PartitionRules:
    """Megatron-style tensor parallelism: shard the MLP hidden and the
    attention heads over `tp`; batch over `dp`; sequence over `sp`."""
    return PartitionRules([
        ("batch", "dp"),
        ("seq", "sp"),
        ("embed", None),
        ("mlp", "tp"),
        ("heads", "tp"),
        ("kv", None),
        ("head_dim", None),
        ("vocab", "tp"),
        ("expert", "ep"),
        ("stage", "pp"),
    ])


def fsdp_rules() -> PartitionRules:
    """ZeRO-3-style fully sharded params: shard the embed axis of every
    weight over `fsdp` (psum_scatter grads, all_gather params on use)."""
    return tp_rules().with_overrides([
        ("embed", "fsdp"),
    ])


def dcn_rules(base: PartitionRules = None) -> PartitionRules:
    """Multi-slice data parallelism: the batch shards over BOTH the DCN
    slice axis and the in-slice dp axis, so XLA reduces gradients
    hierarchically — ring all-reduce over ICI within each slice, then
    one cross-slice all-reduce over DCN per step (the only traffic that
    crosses the slow links; scaling-book multi-slice recipe). Use with
    ``make_multislice_mesh``."""
    return (base or tp_rules()).with_overrides([
        ("batch", ("dp_dcn", "dp")),
    ])


def logical_to_mesh_axes(param_logical: Dict[str, Sequence[Optional[str]]],
                         rules: PartitionRules):
    """Map a pytree-of-logical-axes dict to a dict of PartitionSpecs."""
    return {k: rules.spec(v) for k, v in param_logical.items()}


def named_sharding(mesh, rules: PartitionRules,
                   logical_axes: Sequence[Optional[str]]):
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, rules.spec(logical_axes))


def tree_shardings(mesh, rules: PartitionRules, logical_tree):
    """Pytree of NamedShardings from a matching pytree of logical-axis
    tuples (leaves are tuples/lists of axis names)."""
    import jax
    from jax.sharding import NamedSharding

    def _one(axes):
        return NamedSharding(mesh, rules.spec(axes))

    return jax.tree.map(
        _one, logical_tree,
        is_leaf=lambda x: isinstance(x, (tuple, list)) and all(
            a is None or isinstance(a, str) for a in x))
