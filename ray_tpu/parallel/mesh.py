"""Device mesh construction for TPU slices.

The TPU-native analogue of the reference's process-group bootstrap
(train/torch/config.py:66-153 _setup_torch_process_group): instead of
`dist.init_process_group(nccl)`, parallelism is declared as a
`jax.sharding.Mesh` with named axes, and XLA inserts ICI/DCN collectives
from sharding annotations (scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives).

Axis conventions used across the framework:
  * ``dp``   — data parallel (batch sharding; gradient psum)
  * ``fsdp`` — param/optimizer sharding (ZeRO-equivalent; psum_scatter)
  * ``tp``   — tensor parallel (Megatron partition of matmuls)
  * ``pp``   — pipeline stages
  * ``sp``   — sequence/context parallel (ring attention axis)
  * ``ep``   — expert parallel (MoE all_to_all axis)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "ep", "tp")


@dataclass
class MeshConfig:
    """Declarative mesh shape. Unset axes default to 1. `dp=-1` means
    "absorb all remaining devices" (like the reference ScalingConfig's
    num_workers covering the worker group)."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        fixed = {"fsdp": self.fsdp, "tp": self.tp, "pp": self.pp,
                 "sp": self.sp, "ep": self.ep}
        known = int(np.prod(list(fixed.values())))
        dp = self.dp
        if dp == -1:
            if n_devices % known != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {known}")
            dp = n_devices // known
        total = dp * known
        if total != n_devices:
            raise ValueError(
                f"Mesh shape {dict(dp=dp, **fixed)} needs {total} devices, "
                f"have {n_devices}")
        return {"dp": dp, **fixed}


def best_mesh_shape(n_devices: int, model_parallel: int = 1
                    ) -> Tuple[int, int]:
    """(dp, tp) split for n devices given a model-parallel degree."""
    if n_devices % model_parallel != 0:
        raise ValueError(f"{n_devices} % {model_parallel} != 0")
    return n_devices // model_parallel, model_parallel


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              axis_names: Optional[Sequence[str]] = None):
    """Build a Mesh with the framework's axis names.

    On real hardware, uses jax's device topology ordering
    (mesh_utils.create_device_mesh) so ICI neighbours land adjacent on the
    mesh; on CPU test backends it falls back to a plain reshape.
    """
    import jax
    from jax.sharding import Mesh

    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape_map = config.resolve(len(devices))
    names = tuple(axis_names or [a for a in AXIS_ORDER])
    shape = tuple(shape_map.get(a, 1) for a in names)
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.array(devices))
    except Exception:
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, names)


def slice_count(devices: Optional[Sequence] = None) -> int:
    """Number of TPU slices in the runtime (multi-slice/megascale
    deployments expose `device.slice_index`; single-slice and CPU
    backends count as 1)."""
    import jax
    devices = list(devices if devices is not None else jax.devices())
    indices = {getattr(d, "slice_index", 0) for d in devices}
    return max(1, len(indices))


def make_multislice_mesh(config: Optional[MeshConfig] = None,
                         devices: Optional[Sequence] = None,
                         dcn_axis: str = "dp_dcn",
                         num_slices: Optional[int] = None):
    """Mesh spanning MULTIPLE pod slices: a leading data-parallel axis
    over DCN plus the usual ICI axes within each slice.

    The scaling-book multi-slice recipe: only data parallelism (gradient
    all-reduce once per step) crosses the slow DCN links; tensor/
    sequence/expert axes stay inside a slice on ICI. XLA's megascale
    path lowers collectives over the `dcn_axis` to DCN transfers
    automatically when the mesh is built with slice-aware device
    ordering (jax mesh_utils.create_hybrid_device_mesh).

    On CPU test backends (no slice_index), pass `num_slices` to emulate
    slices as contiguous device groups — the SURVEY §4 CPU-mirror
    pattern, exercised by tests/test_parallel.py and the driver dryrun.

    Reference contrast: the reference has no multi-slice story in-tree —
    its DCN-scale path is torch DDP over NCCL/EFA configured by users
    (train/torch/config.py); here the hybrid mesh IS the API.
    """
    import jax
    from jax.sharding import Mesh

    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n_slices = num_slices or slice_count(devices)
    if n_slices <= 1:
        raise ValueError(
            "make_multislice_mesh needs >1 slice (pass num_slices to "
            "emulate on test backends); use make_mesh for single-slice")
    if len(devices) % n_slices != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_slices} slices")
    per_slice = len(devices) // n_slices
    ici_shape_map = config.resolve(per_slice)
    names = (dcn_axis,) + tuple(AXIS_ORDER)
    ici_shape = tuple(ici_shape_map.get(a, 1) for a in AXIS_ORDER)
    real_slices = all(hasattr(d, "slice_index") for d in devices)
    if real_slices:
        # Real multi-slice hardware: slice-aware ordering is mandatory —
        # a shape error here must SURFACE (a silent contiguous reshape
        # would cut dp_dcn groups across physical slices and route
        # in-slice collectives over DCN).
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, (n_slices,) + (1,) * len(AXIS_ORDER),
            devices=devices)
    else:
        # CPU/test backend: contiguous groups act as slices.
        dev_array = np.array(devices).reshape((n_slices,) + ici_shape)
    return Mesh(dev_array, names)


def make_1d_mesh(axis: str = "dp", devices: Optional[Sequence] = None):
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


def mesh_axis_size(mesh, axis: str) -> int:
    return int(mesh.shape.get(axis, 1))


def local_slice_info() -> Dict[str, object]:
    """Host's view of the slice (reference: tpu.py pod metadata —
    worker id, pod name, chips per host)."""
    import jax
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
