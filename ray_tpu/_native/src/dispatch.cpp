// Native dispatch core: the hot task submit/complete IO path.
//
// Reference analogue: the raylet's asio event loop + core worker RPC
// plumbing (src/ray/raylet/local_task_manager.cc task dispatch hot loop,
// src/ray/core_worker/core_worker.cc task completion path) — collapsed
// into a single epoll IO thread that owns every worker socket.
//
// Why native: on a many-core box the Python epoll mux and the submitter
// thread convoy on the GIL — every completion frame costs a GIL entry,
// every submit costs an inline write(2) while holding the GIL. Here:
//   * sends are enqueued (memcpy, no syscall beyond a coalesced eventfd
//     wake) and written by the IO thread — the submitting Python thread
//     never blocks on socket IO;
//   * frames are parsed off the wire with zero GIL involvement;
//   * Python drains completed frames in BATCHES via disp_recv_batch,
//     which blocks GIL-free (ctypes releases the GIL) and returns many
//     frames per call — one GIL entry amortized over the whole batch.
//
// Wire format matches multiprocessing.Connection framing: 4-byte
// big-endian signed length; -1 escapes to an 8-byte big-endian length.
// Worker conns are AF_UNIX stream sockets (accepted by
// multiprocessing.connection.Listener in scheduler.py WorkerPool).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

struct OutBuf {
  std::vector<uint8_t> data;
  size_t off = 0;
};

struct Frame {
  uint64_t token;
  bool eof;
  std::vector<uint8_t> payload;
};

struct ConnState {
  int fd = -1;  // dup'd, owned by the core
  uint64_t token = 0;
  std::vector<uint8_t> inbuf;
  std::deque<OutBuf> outq;  // guarded by Dispatcher::mu
  // Written by the IO thread (EPOLLOUT arm/disarm in flush_out) and
  // read by app threads on disp_send's inline fast path; atomic so the
  // cross-thread read is defined. Relaxed is enough: the fast path
  // only fires with an empty outq, so any stale read is benign.
  std::atomic<bool> want_write{false};
  bool dead = false;        // IO thread only (after registration)
};

struct Dispatcher {
  int epfd = -1;
  int evfd = -1;  // send-queue / control wakeup
  pthread_t io_thread;
  std::atomic<bool> stopped{false};
  std::atomic<bool> started{false};
  std::atomic<bool> wake_pending{false};

  std::mutex mu;  // guards conns map shape + per-conn outq
  std::unordered_map<uint64_t, std::unique_ptr<ConnState>> conns;
  std::vector<uint64_t> pending_remove;  // freed only by the IO thread

  std::mutex ready_mu;
  std::condition_variable ready_cv;
  std::deque<Frame> ready;

  ~Dispatcher() {
    if (epfd >= 0) close(epfd);
    if (evfd >= 0) close(evfd);
  }
};

void set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags >= 0) fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

void wake_io(Dispatcher* d) {
  // Coalesced: skip the syscall when a wake is already outstanding.
  if (d->wake_pending.exchange(true, std::memory_order_acq_rel)) return;
  uint64_t one = 1;
  ssize_t rc = write(d->evfd, &one, 8);
  (void)rc;
}

void push_ready(Dispatcher* d, Frame&& f) {
  std::lock_guard<std::mutex> lk(d->ready_mu);
  d->ready.push_back(std::move(f));
  d->ready_cv.notify_one();
}

// Parse complete frames out of st->inbuf (IO thread only).
void drain_frames(Dispatcher* d, ConnState* st) {
  auto& buf = st->inbuf;
  size_t pos = 0;
  while (true) {
    if (buf.size() - pos < 4) break;
    int32_t n32;
    memcpy(&n32, buf.data() + pos, 4);
    n32 = (int32_t)ntohl((uint32_t)n32);
    uint64_t n;
    size_t hdr;
    if (n32 == -1) {
      if (buf.size() - pos < 12) break;
      uint64_t be;
      memcpy(&be, buf.data() + pos + 4, 8);
      n = be64toh(be);
      hdr = 12;
    } else {
      n = (uint64_t)n32;
      hdr = 4;
    }
    if (buf.size() - pos < hdr + n) break;
    Frame f;
    f.token = st->token;
    f.eof = false;
    f.payload.assign(buf.begin() + pos + hdr, buf.begin() + pos + hdr + n);
    push_ready(d, std::move(f));
    pos += hdr + n;
  }
  if (pos > 0) buf.erase(buf.begin(), buf.begin() + pos);
}

void conn_kill(Dispatcher* d, ConnState* st) {
  // dead + close under d->mu: disp_send's inline fast path checks
  // `dead` and send()s while holding d->mu, so the fd must not be
  // closed (and potentially reused by another open()) between that
  // check and the write.
  {
    std::lock_guard<std::mutex> lk(d->mu);
    if (st->dead) return;
    st->dead = true;
    epoll_ctl(d->epfd, EPOLL_CTL_DEL, st->fd, nullptr);
    close(st->fd);
  }
  Frame f;
  f.token = st->token;
  f.eof = true;
  push_ready(d, std::move(f));
}

// IO thread only. Returns false when the connection died.
bool flush_out(Dispatcher* d, ConnState* st) {
  while (true) {
    OutBuf* ob = nullptr;
    {
      std::lock_guard<std::mutex> lk(d->mu);
      if (st->outq.empty()) break;
      // deque::push_back (concurrent disp_send) does not invalidate the
      // front element; only this thread pops.
      ob = &st->outq.front();
    }
    ssize_t w = send(st->fd, ob->data.data() + ob->off,
                     ob->data.size() - ob->off, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (!st->want_write) {
          st->want_write = true;
          epoll_event ev{};
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.u64 = st->token;
          epoll_ctl(d->epfd, EPOLL_CTL_MOD, st->fd, &ev);
        }
        return true;
      }
      if (errno == EINTR) continue;
      conn_kill(d, st);
      return false;
    }
    ob->off += (size_t)w;
    if (ob->off == ob->data.size()) {
      std::lock_guard<std::mutex> lk(d->mu);
      st->outq.pop_front();
    }
  }
  if (st->want_write) {
    st->want_write = false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = st->token;
    epoll_ctl(d->epfd, EPOLL_CTL_MOD, st->fd, &ev);
  }
  return true;
}

void* io_loop(void* arg) {
  Dispatcher* d = (Dispatcher*)arg;
  constexpr int kMaxEvents = 128;
  epoll_event events[kMaxEvents];
  std::vector<uint8_t> rdbuf(1 << 20);
  while (!d->stopped.load(std::memory_order_relaxed)) {
    int n = epoll_wait(d->epfd, events, kMaxEvents, 1000);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    // Deferred removals: freed only here so event handling below can
    // safely use raw ConnState pointers within one loop iteration.
    {
      std::vector<uint64_t> removes;
      {
        std::lock_guard<std::mutex> lk(d->mu);
        removes.swap(d->pending_remove);
      }
      for (uint64_t token : removes) {
        std::unique_ptr<ConnState> st;
        {
          std::lock_guard<std::mutex> lk(d->mu);
          auto it = d->conns.find(token);
          if (it == d->conns.end()) continue;
          st = std::move(it->second);
          d->conns.erase(it);
        }
        if (!st->dead) {
          epoll_ctl(d->epfd, EPOLL_CTL_DEL, st->fd, nullptr);
          close(st->fd);
        }
      }
    }
    bool flush_all = false;
    for (int i = 0; i < n; i++) {
      if (events[i].data.u64 == UINT64_MAX) {
        uint64_t v;
        while (read(d->evfd, &v, 8) == 8) {
        }
        d->wake_pending.store(false, std::memory_order_release);
        flush_all = true;
        continue;
      }
      uint64_t token = events[i].data.u64;
      ConnState* st = nullptr;
      {
        std::lock_guard<std::mutex> lk(d->mu);
        auto it = d->conns.find(token);
        if (it != d->conns.end()) st = it->second.get();
      }
      if (st == nullptr || st->dead) continue;
      uint32_t evs = events[i].events;
      if (evs & EPOLLOUT) {
        if (!flush_out(d, st)) continue;
      }
      if (evs & (EPOLLIN | EPOLLHUP | EPOLLERR)) {
        bool eof = false;
        while (true) {
          ssize_t r = recv(st->fd, rdbuf.data(), rdbuf.size(), 0);
          if (r < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            if (errno == EINTR) continue;
            eof = true;
            break;
          }
          if (r == 0) {
            eof = true;
            break;
          }
          st->inbuf.insert(st->inbuf.end(), rdbuf.data(), rdbuf.data() + r);
          if ((size_t)r < rdbuf.size()) break;
        }
        drain_frames(d, st);
        if (eof) conn_kill(d, st);
      }
    }
    if (flush_all) {
      std::vector<ConnState*> flushers;
      {
        std::lock_guard<std::mutex> lk(d->mu);
        flushers.reserve(d->conns.size());
        for (auto& [tok, st] : d->conns)
          if (!st->dead && !st->outq.empty()) flushers.push_back(st.get());
      }
      for (ConnState* st : flushers) flush_out(d, st);
    }
  }
  return nullptr;
}

}  // namespace

extern "C" {

void* disp_create() {
  auto* d = new Dispatcher();
  d->epfd = epoll_create1(EPOLL_CLOEXEC);
  d->evfd = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (d->epfd < 0 || d->evfd < 0) {
    delete d;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX;  // sentinel: the eventfd
  epoll_ctl(d->epfd, EPOLL_CTL_ADD, d->evfd, &ev);
  if (pthread_create(&d->io_thread, nullptr, io_loop, d) != 0) {
    delete d;
    return nullptr;
  }
  d->started.store(true);
  return d;
}

// Registers a connection synchronously (epoll_ctl is thread-safe): by
// the time this returns, disp_send on the token succeeds. The core
// dup()s the fd; the caller's copy stays open for any legacy writers.
int disp_add(void* h, int fd, uint64_t token) {
  auto* d = (Dispatcher*)h;
  int dup_fd = dup(fd);
  if (dup_fd < 0) return -1;
  set_nonblocking(dup_fd);
  auto st = std::make_unique<ConnState>();
  st->fd = dup_fd;
  st->token = token;
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = token;
  if (epoll_ctl(d->epfd, EPOLL_CTL_ADD, dup_fd, &ev) != 0) {
    close(dup_fd);
    return -1;
  }
  std::lock_guard<std::mutex> lk(d->mu);
  d->conns[token] = std::move(st);
  return 0;
}

int disp_remove(void* h, uint64_t token) {
  auto* d = (Dispatcher*)h;
  {
    std::lock_guard<std::mutex> lk(d->mu);
    d->pending_remove.push_back(token);
  }
  wake_io(d);
  return 0;
}

// Enqueue one framed message (copies `data`; framing header added
// here). Returns 0 on success, -1 if the token is unknown/dead.
int disp_send(void* h, uint64_t token, const void* data, uint64_t len) {
  auto* d = (Dispatcher*)h;
  OutBuf ob;
  if (len < 0x7FFFFFFFull) {
    ob.data.resize(4 + len);
    uint32_t be = htonl((uint32_t)len);
    memcpy(ob.data.data(), &be, 4);
    memcpy(ob.data.data() + 4, data, len);
  } else {
    ob.data.resize(12 + len);
    uint32_t esc = htonl((uint32_t)-1);
    memcpy(ob.data.data(), &esc, 4);
    uint64_t be = htobe64(len);
    memcpy(ob.data.data() + 4, &be, 8);
    memcpy(ob.data.data() + 12, data, len);
  }
  {
    std::lock_guard<std::mutex> lk(d->mu);
    auto it = d->conns.find(token);
    if (it == d->conns.end() || it->second->dead) return -1;
    ConnState* st = it->second.get();
    if (st->outq.empty() && !st->want_write) {
      // Inline non-blocking write: the uncontended common case skips
      // the IO-thread handoff entirely (eventfd wake + two context
      // switches per frame — the dominant per-task cost on small
      // hosts). ONE send attempt only — d->mu is dispatcher-global,
      // so looping a multi-MB frame to completion here would stall
      // every other connection; a partial write enqueues the
      // remainder for the IO thread. Ordering holds because the queue
      // is empty and we hold d->mu, which flush_out's queue
      // inspection also takes. Errors fall through to the enqueue
      // path so conn death is handled in one place (flush_out ->
      // conn_kill).
      ssize_t w = send(st->fd, ob.data.data(), ob.data.size(),
                       MSG_NOSIGNAL);
      if (w >= 0) {
        if ((size_t)w == ob.data.size()) return 0;
        ob.off = (size_t)w;
      }
    }
    st->outq.push_back(std::move(ob));
  }
  wake_io(d);
  return 0;
}

// Drain completed frames into `buf` as records:
//   [u64 token][u64 len][len payload bytes]      (normal frame)
//   [u64 token][u64 0xFFFFFFFFFFFFFFFF]          (EOF record)
// Blocks up to timeout_ms when nothing is ready. Returns bytes written,
// 0 on timeout, -(required_size) when the first frame alone exceeds cap.
int64_t disp_recv_batch(void* h, void* buf, uint64_t cap, int timeout_ms) {
  auto* d = (Dispatcher*)h;
  std::unique_lock<std::mutex> lk(d->ready_mu);
  if (d->ready.empty()) {
    d->ready_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [d] {
      return !d->ready.empty() || d->stopped.load(std::memory_order_relaxed);
    });
  }
  if (d->ready.empty()) return 0;
  uint8_t* out = (uint8_t*)buf;
  uint64_t used = 0;
  while (!d->ready.empty()) {
    Frame& f = d->ready.front();
    uint64_t need = f.eof ? 16 : 16 + f.payload.size();
    if (used + need > cap) {
      if (used == 0) return -(int64_t)need;
      break;
    }
    memcpy(out + used, &f.token, 8);
    uint64_t len = f.eof ? UINT64_MAX : (uint64_t)f.payload.size();
    memcpy(out + used + 8, &len, 8);
    if (!f.eof) memcpy(out + used + 16, f.payload.data(), f.payload.size());
    used += need;
    d->ready.pop_front();
  }
  return (int64_t)used;
}

void disp_stop(void* h) {
  auto* d = (Dispatcher*)h;
  d->stopped.store(true);
  wake_io(d);
  {
    std::lock_guard<std::mutex> lk(d->ready_mu);
    d->ready_cv.notify_all();
  }
}

void disp_destroy(void* h) {
  auto* d = (Dispatcher*)h;
  disp_stop(h);
  if (d->started.load()) pthread_join(d->io_thread, nullptr);
  {
    std::lock_guard<std::mutex> lk(d->mu);
    for (auto& [tok, st] : d->conns)
      if (!st->dead) close(st->fd);
    d->conns.clear();
  }
  delete d;
}

}  // extern "C"
