// Non-temporal (streaming) memory copy for the object-store put path.
//
// The glibc memcpy only switches to non-temporal stores above a
// threshold tied to L3 size (~3/4 of the shared cache): a store-sized
// put (tens to a few hundred MB) below that threshold write-allocates
// every destination line, reading the destination once just to
// overwrite it — measured 6.1 GB/s vs 14.6 GB/s with explicit
// streaming stores for a 256 MB segment copy on the bench host. Put
// destinations are written exactly once and read (if ever) much later
// from another process, so bypassing the cache is always right here.
//
// SSE2 is part of the x86-64 baseline, so no runtime dispatch is
// needed; non-x86 builds degrade to plain memcpy.

#include <cstdint>
#include <cstring>

#if defined(__SSE2__)
#include <emmintrin.h>
#endif

extern "C" void rt_nt_copy(void* dst, const void* src, uint64_t n) {
#if defined(__SSE2__)
    char* d = static_cast<char*>(dst);
    const char* s = static_cast<const char*>(src);
    // Streaming stores require 16B alignment; align the DESTINATION to
    // a full cache line and take unaligned loads (loadu) on the source
    // — put sources are arbitrary user buffers, destinations are
    // 64B-aligned segment offsets (serialization._ALIGN).
    uint64_t head = (64 - (reinterpret_cast<uintptr_t>(d) & 63)) & 63;
    if (head > n) head = n;
    if (head) { memcpy(d, s, head); d += head; s += head; n -= head; }
    uint64_t body = n & ~uint64_t(63);
    for (uint64_t i = 0; i < body; i += 64) {
        __m128i a = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i));
        __m128i b = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 16));
        __m128i c = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 32));
        __m128i e = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 48));
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i), a);
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 16), b);
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 32), c);
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 48), e);
    }
    // Order the streaming stores before any later load/seal: readers in
    // other processes must never observe a sealed-but-unflushed line.
    _mm_sfence();
    if (n - body) memcpy(d + body, s + body, n - body);
#else
    memcpy(dst, src, n);
#endif
}
