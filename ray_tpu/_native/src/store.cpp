// Native shared-memory object store (plasma equivalent).
//
// Reference: src/ray/object_manager/plasma/ — ObjectStore
// (object_store.cc), PlasmaAllocator over dlmalloc (plasma_allocator.cc,
// dlmalloc.cc), ObjectLifecycleManager + LRU EvictionPolicy
// (object_lifecycle_manager.cc, eviction_policy.cc). Re-designed without
// a store daemon: ONE mmap'd arena file under /dev/shm shared by every
// process; a process-shared robust mutex guards a boundary-tag first-fit
// allocator and an open-addressing object index living inside the arena
// itself (so any process can create/seal/get/release without RPC — the
// fd-passing protocol of plasma's fling.cc is unnecessary when everyone
// maps the same file).
//
// Layout:
//   [Header | index slots | heap ...]
// Heap blocks carry size+prev_size boundary tags for O(1) coalescing.
// Eviction: sealed refcount==0 objects are reclaimed in LRU order when
// an allocation fails (eviction_policy.cc semantics).
//
// All cross-process references are OFFSETS from the arena base, never
// pointers. C ABI at the bottom; Python binds with ctypes and reads
// object payloads zero-copy through its own mmap of the same file.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545053544f5245ULL;  // "RTPSTORE"
constexpr uint32_t kIdLen = 16;
constexpr uint32_t kSlots = 1 << 15;        // index capacity (open addr)
constexpr uint64_t kAlign = 64;             // block alignment (cacheline)

enum SlotState : uint32_t {
  SLOT_FREE = 0,
  SLOT_TOMB = 1,
  SLOT_CREATED = 2,   // allocated, being written
  SLOT_SEALED = 3,    // immutable, readable
};

struct Slot {
  uint8_t id[kIdLen];
  uint32_t state;
  int32_t refcount;
  uint64_t offset;     // payload offset from arena base
  uint64_t size;       // payload size
  uint64_t lru_tick;   // last-touch tick for eviction order
};

struct BlockHeader {
  uint64_t size;       // payload capacity of this block (excl. header)
  uint64_t prev_size;  // size of previous block's payload (0 if first)
  uint32_t used;       // 1 = allocated
  uint32_t pad;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // total file size
  uint64_t heap_off;       // offset of first block header
  uint64_t heap_end;       // end offset of heap
  uint64_t used_bytes;     // allocated payload bytes
  uint64_t num_objects;
  uint64_t lru_clock;
  uint64_t evictions;
  pthread_mutex_t lock;    // process-shared robust mutex
  Slot slots[kSlots];
};

struct Store {
  int fd;
  uint8_t* base;
  uint64_t size;
  Header* hdr;
};

inline BlockHeader* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(s->base + off);
}

inline uint64_t payload_off(uint64_t block_off) {
  return block_off + sizeof(BlockHeader);
}

inline uint64_t next_block_off(uint64_t block_off, BlockHeader* b) {
  return block_off + sizeof(BlockHeader) + b->size;
}

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

// FNV-1a over the id for index hashing.
uint64_t hash_id(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) { h ^= id[i]; h *= 1099511628211ULL; }
  return h;
}

struct Guard {
  pthread_mutex_t* m;
  explicit Guard(pthread_mutex_t* mu) : m(mu) {
    int rc = pthread_mutex_lock(m);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(m);  // robust recovery
  }
  ~Guard() { pthread_mutex_unlock(m); }
};

Slot* find_slot(Header* h, const uint8_t* id) {
  uint64_t idx = hash_id(id) & (kSlots - 1);
  for (uint32_t probe = 0; probe < kSlots; probe++) {
    Slot* s = &h->slots[(idx + probe) & (kSlots - 1)];
    if (s->state == SLOT_FREE) return nullptr;
    if (s->state != SLOT_TOMB && memcmp(s->id, id, kIdLen) == 0) return s;
  }
  return nullptr;
}

Slot* insert_slot(Header* h, const uint8_t* id) {
  uint64_t idx = hash_id(id) & (kSlots - 1);
  Slot* tomb = nullptr;
  for (uint32_t probe = 0; probe < kSlots; probe++) {
    Slot* s = &h->slots[(idx + probe) & (kSlots - 1)];
    if (s->state == SLOT_FREE) {
      Slot* target = tomb ? tomb : s;
      memcpy(target->id, id, kIdLen);
      return target;
    }
    if (s->state == SLOT_TOMB) { if (!tomb) tomb = s; continue; }
    if (memcmp(s->id, id, kIdLen) == 0) return nullptr;  // exists
  }
  if (tomb) { memcpy(tomb->id, id, kIdLen); return tomb; }
  return nullptr;  // table full
}

// -- allocator (boundary-tag first fit, reference: dlmalloc.cc role) ------
int64_t alloc_block(Store* st, uint64_t want) {
  want = align_up(want < kAlign ? kAlign : want, kAlign);
  Header* h = st->hdr;
  uint64_t off = h->heap_off;
  while (off + sizeof(BlockHeader) <= h->heap_end) {
    BlockHeader* b = block_at(st, off);
    if (!b->used && b->size >= want) {
      // split when the remainder can hold a minimal block
      if (b->size >= want + sizeof(BlockHeader) + kAlign) {
        uint64_t rest = b->size - want - sizeof(BlockHeader);
        b->size = want;
        uint64_t noff = next_block_off(off, b);
        BlockHeader* nb = block_at(st, noff);
        nb->size = rest;
        nb->prev_size = want;
        nb->used = 0;
        uint64_t after = next_block_off(noff, nb);
        if (after + sizeof(BlockHeader) <= h->heap_end)
          block_at(st, after)->prev_size = rest;
      }
      b->used = 1;
      h->used_bytes += b->size;
      return static_cast<int64_t>(payload_off(off));
    }
    off = next_block_off(off, b);
  }
  return -1;
}

void free_block(Store* st, uint64_t pay_off) {
  Header* h = st->hdr;
  uint64_t off = pay_off - sizeof(BlockHeader);
  BlockHeader* b = block_at(st, off);
  b->used = 0;
  h->used_bytes -= b->size;
  // coalesce with next
  uint64_t noff = next_block_off(off, b);
  if (noff + sizeof(BlockHeader) <= h->heap_end) {
    BlockHeader* nb = block_at(st, noff);
    if (!nb->used) {
      b->size += sizeof(BlockHeader) + nb->size;
      uint64_t after = next_block_off(off, b);
      if (after + sizeof(BlockHeader) <= h->heap_end)
        block_at(st, after)->prev_size = b->size;
    }
  }
  // coalesce with prev
  if (b->prev_size != 0) {
    uint64_t poff = off - sizeof(BlockHeader) - b->prev_size;
    BlockHeader* pb = block_at(st, poff);
    if (!pb->used) {
      pb->size += sizeof(BlockHeader) + b->size;
      uint64_t after = next_block_off(poff, pb);
      if (after + sizeof(BlockHeader) <= h->heap_end)
        block_at(st, after)->prev_size = pb->size;
    }
  }
}

// Evict one LRU sealed, unreferenced object. Caller holds the lock.
bool evict_one(Store* st) {
  Header* h = st->hdr;
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < kSlots; i++) {
    Slot* s = &h->slots[i];
    if (s->state == SLOT_SEALED && s->refcount <= 0) {
      if (!victim || s->lru_tick < victim->lru_tick) victim = s;
    }
  }
  if (!victim) return false;
  free_block(st, victim->offset);
  victim->state = SLOT_TOMB;
  h->num_objects--;
  h->evictions++;
  return true;
}

}  // namespace

extern "C" {

Store* rt_store_create(const char* path, uint64_t capacity) {
  if (capacity < sizeof(Header) + (1 << 20)) capacity = sizeof(Header) + (1 << 20);
  int fd = open(path, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(capacity)) != 0) { close(fd); return nullptr; }
  void* base = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }
  Store* st = new Store{fd, static_cast<uint8_t*>(base), capacity, nullptr};
  Header* h = reinterpret_cast<Header*>(base);
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->heap_off = align_up(sizeof(Header), kAlign);
  h->heap_end = capacity;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->lock, &attr);
  pthread_mutexattr_destroy(&attr);
  BlockHeader* first = block_at(st, h->heap_off);
  first->size = h->heap_end - h->heap_off - sizeof(BlockHeader);
  first->prev_size = 0;
  first->used = 0;
  h->magic = kMagic;  // publish last
  st->hdr = h;
  return st;
}

Store* rt_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat sb;
  if (fstat(fd, &sb) != 0) { close(fd); return nullptr; }
  void* base = mmap(nullptr, sb.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic) { munmap(base, sb.st_size); close(fd); return nullptr; }
  return new Store{fd, static_cast<uint8_t*>(base),
                   static_cast<uint64_t>(sb.st_size), h};
}

// Reserve space for an object; returns payload offset or -1.
// (plasma Create; two-phase create/seal like plasma's CreateObject.)
int64_t rt_store_create_obj(Store* st, const uint8_t* id, uint64_t size) {
  Guard g(&st->hdr->lock);
  if (find_slot(st->hdr, id)) return -2;  // duplicate
  int64_t off = alloc_block(st, size);
  while (off < 0) {
    if (!evict_one(st)) return -1;       // full, nothing evictable
    off = alloc_block(st, size);
  }
  Slot* s = insert_slot(st->hdr, id);
  if (!s) { free_block(st, off); return -3; }  // index full
  s->state = SLOT_CREATED;
  s->refcount = 1;                        // creator holds a ref
  s->offset = static_cast<uint64_t>(off);
  s->size = size;
  s->lru_tick = ++st->hdr->lru_clock;
  st->hdr->num_objects++;
  return off;
}

int rt_store_seal(Store* st, const uint8_t* id) {
  Guard g(&st->hdr->lock);
  Slot* s = find_slot(st->hdr, id);
  if (!s || s->state != SLOT_CREATED) return -1;
  s->state = SLOT_SEALED;
  return 0;
}

// One-shot put = create + memcpy + seal.
int64_t rt_store_put(Store* st, const uint8_t* id, const void* data,
                     uint64_t size) {
  int64_t off = rt_store_create_obj(st, id, size);
  if (off < 0) return off;
  memcpy(st->base + off, data, size);
  rt_store_seal(st, id);
  return off;
}

// Lookup: fills offset/size, increfs (pin for reading). Returns 0, or -1.
int rt_store_get(Store* st, const uint8_t* id, uint64_t* off_out,
                 uint64_t* size_out) {
  Guard g(&st->hdr->lock);
  Slot* s = find_slot(st->hdr, id);
  if (!s || s->state != SLOT_SEALED) return -1;
  s->refcount++;
  s->lru_tick = ++st->hdr->lru_clock;
  *off_out = s->offset;
  *size_out = s->size;
  return 0;
}

int rt_store_contains(Store* st, const uint8_t* id) {
  Guard g(&st->hdr->lock);
  Slot* s = find_slot(st->hdr, id);
  return (s && s->state == SLOT_SEALED) ? 1 : 0;
}

// Drop a pin (reader done / creator done). Objects with refcount 0 stay
// sealed until evicted or deleted (plasma Release semantics).
int rt_store_release(Store* st, const uint8_t* id) {
  Guard g(&st->hdr->lock);
  Slot* s = find_slot(st->hdr, id);
  if (!s || s->state < SLOT_CREATED) return -1;
  if (s->refcount > 0) s->refcount--;
  return 0;
}

// Owner-driven delete (refcount went to 0 cluster-wide).
int rt_store_delete(Store* st, const uint8_t* id) {
  Guard g(&st->hdr->lock);
  Slot* s = find_slot(st->hdr, id);
  if (!s || s->state < SLOT_CREATED) return -1;
  if (s->refcount > 0) return -2;  // pinned by a reader
  free_block(st, s->offset);
  s->state = SLOT_TOMB;
  st->hdr->num_objects--;
  return 0;
}

uint64_t rt_store_used(Store* st) { return st->hdr->used_bytes; }
uint64_t rt_store_capacity(Store* st) { return st->hdr->capacity; }
uint64_t rt_store_num_objects(Store* st) { return st->hdr->num_objects; }
uint64_t rt_store_evictions(Store* st) { return st->hdr->evictions; }

void rt_store_close(Store* st) {
  munmap(st->base, st->size);
  close(st->fd);
  delete st;
}

int rt_store_unlink(const char* path) { return unlink(path); }

uint8_t* rt_store_base_ptr(Store* st) { return st->base; }

}  // extern "C"
