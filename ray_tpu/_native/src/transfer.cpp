// Chunked node-to-node object transfer over TCP.
//
// Reference: src/ray/object_manager/ — ObjectManager (object_manager.h:117)
// with PushManager/PullManager moving objects between nodes' plasma stores
// in chunks over gRPC (object_buffer_pool.h chunking). Re-designed to a
// minimal pull protocol (no gRPC dependency): a per-node server thread
// serves GET <id> straight out of the local arena (store.cpp); the client
// pulls into its own arena with create/seal, chunked so huge objects
// never need a contiguous userspace staging buffer.
//
// Wire format (little-endian):
//   request:  [16B id]
//   response: [u64 size | payload]  (size == UINT64_MAX => not found)
//
// DCN/ICI note: this path carries HOST objects (control data, CPU
// arrays). Device tensors never travel here — they move inside XLA
// programs over ICI (SURVEY §2.1 translation note).

#include <cstdint>
#include <cstring>
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <sys/socket.h>
#include <unistd.h>

extern "C" {
struct Store;
int64_t rt_store_create_obj(Store*, const uint8_t*, uint64_t);
int rt_store_seal(Store*, const uint8_t*);
int rt_store_get(Store*, const uint8_t*, uint64_t*, uint64_t*);
int rt_store_release(Store*, const uint8_t*);
uint8_t* rt_store_base_ptr(Store*);
}

namespace {

constexpr uint32_t kIdLen = 16;
constexpr uint64_t kChunk = 1 << 20;  // 1 MiB chunks

struct Server {
  Store* store;
  int listen_fd;
  uint16_t port;
  pthread_t thread;
  volatile bool stop;
};

bool read_exact(int fd, void* buf, uint64_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r; n -= static_cast<uint64_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, uint64_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r; n -= static_cast<uint64_t>(r);
  }
  return true;
}

void serve_conn(Server* sv, int cfd) {
  uint8_t id[kIdLen];
  while (read_exact(cfd, id, kIdLen)) {
    uint64_t off = 0, size = 0;
    if (rt_store_get(sv->store, id, &off, &size) != 0) {
      uint64_t missing = UINT64_MAX;
      if (!write_exact(cfd, &missing, 8)) break;
      continue;
    }
    bool ok = write_exact(cfd, &size, 8);
    uint8_t* base = rt_store_base_ptr(sv->store);
    for (uint64_t sent = 0; ok && sent < size; sent += kChunk) {
      uint64_t n = size - sent < kChunk ? size - sent : kChunk;
      ok = write_exact(cfd, base + off + sent, n);
    }
    rt_store_release(sv->store, id);  // drop the read pin
    if (!ok) break;
  }
  close(cfd);
}

void* server_loop(void* arg) {
  Server* sv = static_cast<Server*>(arg);
  while (!sv->stop) {
    int cfd = accept(sv->listen_fd, nullptr, nullptr);
    if (cfd < 0) { if (sv->stop) break; continue; }
    int one = 1;
    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    serve_conn(sv, cfd);
  }
  return nullptr;
}

}  // namespace

extern "C" {

Server* rt_transfer_serve(Store* store, uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  Server* sv = new Server{store, fd, ntohs(addr.sin_port), {}, false};
  pthread_create(&sv->thread, nullptr, server_loop, sv);
  return sv;
}

uint16_t rt_transfer_port(Server* sv) { return sv->port; }

void rt_transfer_stop(Server* sv) {
  sv->stop = true;
  shutdown(sv->listen_fd, SHUT_RDWR);
  close(sv->listen_fd);
  pthread_join(sv->thread, nullptr);
  delete sv;
}

// Pull one object from a remote node into the local store.
// Returns 0 ok, -1 connect error, -2 not found remotely, -3 local alloc.
int rt_transfer_pull(Store* local, const char* host, uint16_t port,
                     const uint8_t* id) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int rc = -1;
  do {
    if (!write_exact(fd, id, kIdLen)) break;
    uint64_t size = 0;
    if (!read_exact(fd, &size, 8)) break;
    if (size == UINT64_MAX) { rc = -2; break; }
    int64_t off = rt_store_create_obj(local, id, size);
    if (off == -2) { rc = 0; break; }  // already present locally
    if (off < 0) { rc = -3; break; }
    uint8_t* base = rt_store_base_ptr(local);
    bool ok = true;
    for (uint64_t got = 0; ok && got < size; got += kChunk) {
      uint64_t n = size - got < kChunk ? size - got : kChunk;
      ok = read_exact(fd, base + off + got, n);
    }
    if (!ok) break;
    rt_store_seal(local, id);
    rt_store_release(local, id);  // drop creator pin; owner managed now
    rc = 0;
  } while (false);
  close(fd);
  return rc;
}

}  // extern "C"
