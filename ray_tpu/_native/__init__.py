"""ctypes bindings for the native (C++) runtime components.

Reference: the role of _raylet.pyx — binding Python to the C++ layer —
without Cython (not baked into this image): a plain C ABI + ctypes.

Builds lazily with g++ on first use (cached as _native/libray_tpu.so,
rebuilt when sources are newer). Everything degrades gracefully: callers
check `available()` and fall back to the pure-Python paths.
"""
import ctypes
import mmap as _mmap
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = [os.path.join(_DIR, "src", f)
        for f in ("store.cpp", "transfer.cpp", "dispatch.cpp",
                  "memcopy.cpp")]
_SO = os.path.join(_DIR, "libray_tpu.so")
_lock = threading.Lock()
_lib = None
_build_error: Optional[str] = None


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    return any(os.path.getmtime(s) > so_m for s in _SRC)


def _build():
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-pthread", "-std=c++17",
           "-o", _SO + ".tmp"] + _SRC
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    os.replace(_SO + ".tmp", _SO)


def _load():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if _needs_build():
                _build()
            lib = ctypes.CDLL(_SO)
        except Exception as e:  # noqa: BLE001
            _build_error = str(e)
            return None
        # signatures
        lib.rt_store_create.restype = ctypes.c_void_p
        lib.rt_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rt_store_open.restype = ctypes.c_void_p
        lib.rt_store_open.argtypes = [ctypes.c_char_p]
        lib.rt_store_create_obj.restype = ctypes.c_int64
        lib.rt_store_create_obj.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.rt_store_seal.restype = ctypes.c_int
        lib.rt_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_put.restype = ctypes.c_int64
        lib.rt_store_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_void_p, ctypes.c_uint64]
        lib.rt_store_get.restype = ctypes.c_int
        lib.rt_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.rt_store_contains.restype = ctypes.c_int
        lib.rt_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_release.restype = ctypes.c_int
        lib.rt_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_store_delete.restype = ctypes.c_int
        lib.rt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        for f in ("rt_store_used", "rt_store_capacity",
                  "rt_store_num_objects", "rt_store_evictions"):
            getattr(lib, f).restype = ctypes.c_uint64
            getattr(lib, f).argtypes = [ctypes.c_void_p]
        lib.rt_store_close.restype = None
        lib.rt_store_close.argtypes = [ctypes.c_void_p]
        lib.rt_store_unlink.argtypes = [ctypes.c_char_p]
        lib.rt_transfer_serve.restype = ctypes.c_void_p
        lib.rt_transfer_serve.argtypes = [ctypes.c_void_p, ctypes.c_uint16]
        lib.rt_transfer_port.restype = ctypes.c_uint16
        lib.rt_transfer_port.argtypes = [ctypes.c_void_p]
        lib.rt_transfer_stop.restype = None
        lib.rt_transfer_stop.argtypes = [ctypes.c_void_p]
        lib.rt_transfer_pull.restype = ctypes.c_int
        lib.rt_transfer_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16,
            ctypes.c_char_p]
        lib.disp_create.restype = ctypes.c_void_p
        lib.disp_create.argtypes = []
        lib.disp_recv_batch.restype = ctypes.c_int64
        lib.disp_recv_batch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_uint64, ctypes.c_int]
        lib.disp_stop.restype = None
        lib.disp_stop.argtypes = [ctypes.c_void_p]
        lib.disp_destroy.restype = None
        lib.disp_destroy.argtypes = [ctypes.c_void_p]
        # Quick dispatch entry points go through PyDLL: they only
        # memcpy + enqueue + (maybe) one eventfd write, so releasing
        # the GIL around them costs more (a handoff/context-switch
        # opportunity per call) than it buys.
        qlib = ctypes.PyDLL(_SO)
        qlib.disp_add.restype = ctypes.c_int
        qlib.disp_add.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_uint64]
        qlib.disp_remove.restype = ctypes.c_int
        qlib.disp_remove.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        qlib.disp_send.restype = ctypes.c_int
        qlib.disp_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_char_p, ctypes.c_uint64]
        lib.rt_nt_copy.restype = None
        lib.rt_nt_copy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_uint64]
        lib._qlib = qlib
        _lib = lib
        return _lib


EOF_LEN = 0xFFFFFFFFFFFFFFFF


class NativeDispatcher:
    """Thin handle to the C++ dispatch core (dispatch.cpp): an epoll IO
    thread owning worker sockets. Sends enqueue without syscalls on the
    caller; receives drain in batches with one GIL entry per batch."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self._send = lib._qlib.disp_send
        self._h = lib.disp_create()
        if not self._h:
            raise RuntimeError("disp_create failed")

    def add(self, fd: int, token: int) -> bool:
        return self._lib._qlib.disp_add(self._h, fd, token) == 0

    def remove(self, token: int) -> None:
        self._lib._qlib.disp_remove(self._h, token)

    def send(self, token: int, data: bytes) -> bool:
        return self._send(self._h, token, data, len(data)) == 0

    def recv_batch(self, buf, cap: int, timeout_ms: int) -> int:
        """Fills `buf` (a ctypes char array) with framed records; see
        dispatch.cpp disp_recv_batch. Blocks GIL-free in C++."""
        return int(self._lib.disp_recv_batch(self._h, buf, cap, timeout_ms))

    def stop(self) -> None:
        if self._h:
            self._lib.disp_stop(self._h)

    def destroy(self) -> None:
        if self._h:
            self._lib.disp_destroy(self._h)
            self._h = None


def available() -> bool:
    return _load() is not None


def _buf_addr_len(view: memoryview):
    """(address, nbytes) of a contiguous 1-D byte view via numpy's
    buffer introspection (works on read-only exporters, unlike
    ``ctypes.from_buffer``). The returned address is only valid while
    `view` itself is alive — callers must keep the view referenced
    across the native call and drop the array before closing any
    backing mmap (the frombuffer array holds a buffer export)."""
    import numpy as np
    arr = np.frombuffer(view, dtype=np.uint8)
    return arr, arr.ctypes.data, arr.nbytes


def nt_copy(dst: memoryview, src) -> bool:
    """Copy `src` into `dst` with non-temporal stores (memcopy.cpp),
    bypassing the write-allocate penalty glibc memcpy pays below its
    NT threshold — the put path's single copy into a store segment.
    Returns False (caller falls back to a plain slice copy) when the
    native lib is unavailable; lengths must already match."""
    lib = _load()
    if lib is None:
        return False
    sview = src if isinstance(src, memoryview) else memoryview(src)
    if sview.format != "B" or sview.ndim != 1:
        sview = sview.cast("B")
    da, daddr, dlen = _buf_addr_len(dst)
    sa, saddr, slen = _buf_addr_len(sview)
    if dlen != slen:
        raise ValueError(f"nt_copy length mismatch: {dlen} != {slen}")
    if dlen:
        lib.rt_nt_copy(daddr, saddr, dlen)
    del da, sa  # release the buffer exports before returning
    return True


def build_error() -> Optional[str]:
    _load()
    return _build_error


class NativeStore:
    """Python handle to the C++ arena store (plasma-client equivalent).

    Reads are zero-copy: Python maps the same arena file and returns
    memoryview slices at the offsets the C side hands out.
    """

    def __init__(self, path: str, capacity: Optional[int] = None,
                 create: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_build_error}")
        self._lib = lib
        self.path = path
        if create:
            self._h = lib.rt_store_create(path.encode(),
                                          int(capacity or (1 << 30)))
        else:
            self._h = lib.rt_store_open(path.encode())
        if not self._h:
            raise RuntimeError(
                f"failed to {'create' if create else 'open'} arena {path}")
        fd = os.open(path, os.O_RDWR)
        try:
            self._map = _mmap.mmap(fd, os.path.getsize(path))
        finally:
            os.close(fd)
        self._view = memoryview(self._map)

    # -- object API --------------------------------------------------------
    @staticmethod
    def _key(object_id) -> bytes:
        b = object_id if isinstance(object_id, bytes) else object_id.binary()
        if len(b) != 16:
            raise ValueError(f"ids must be 16 bytes, got {len(b)}")
        return b

    def put(self, object_id, data) -> int:
        if not self._h:
            raise RuntimeError("store closed")
        data = bytes(data) if not isinstance(data, (bytes, bytearray,
                                                    memoryview)) else data
        buf = (ctypes.c_char * len(data)).from_buffer_copy(data)
        off = self._lib.rt_store_put(self._h, self._key(object_id),
                                     buf, len(data))
        if off == -2:
            raise FileExistsError("object already in store")
        if off < 0:
            raise MemoryError(f"arena full (rc={off})")
        return off

    def create(self, object_id, size: int) -> memoryview:
        """Two-phase create: returns a writable view; call seal() after."""
        if not self._h:
            raise RuntimeError("store closed")
        off = self._lib.rt_store_create_obj(self._h, self._key(object_id),
                                            size)
        if off == -2:
            raise FileExistsError("object already in store")
        if off < 0:
            raise MemoryError(f"arena full (rc={off})")
        return self._view[off:off + size]

    def seal(self, object_id):
        if not self._h:
            return
        if self._lib.rt_store_seal(self._h, self._key(object_id)) != 0:
            raise KeyError("seal: object not in CREATED state")

    def locate(self, object_id):
        """(offset, size) of the object inside the arena file; PINS the
        object (call release() when done) so the slot cannot be
        recycled while a reader (zero-copy view or same-host peer
        reading the file directly) is live."""
        if not self._h:
            raise KeyError("store closed")
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rt_store_get(self._h, self._key(object_id),
                                    ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            raise KeyError("object not found/sealed")
        return off.value, size.value

    def get(self, object_id) -> memoryview:
        """Zero-copy read view; pins the object (call release() when
        done, plasma client semantics)."""
        off, size = self.locate(object_id)
        return self._view[off:off + size]

    def contains(self, object_id) -> bool:
        if not self._h:
            return False
        return bool(self._lib.rt_store_contains(self._h,
                                                self._key(object_id)))

    def release(self, object_id):
        # Pins can outlive an explicit close() (live zero-copy views at
        # shutdown); a released handle must be a no-op, not a segfault.
        if not self._h:
            return
        self._lib.rt_store_release(self._h, self._key(object_id))

    def delete(self, object_id):
        if not self._h:
            return
        rc = self._lib.rt_store_delete(self._h, self._key(object_id))
        if rc == -2:
            raise RuntimeError("object pinned by a reader")

    # -- stats -------------------------------------------------------------
    def used_bytes(self) -> int:
        return self._lib.rt_store_used(self._h) if self._h else 0

    def capacity(self) -> int:
        return self._lib.rt_store_capacity(self._h) if self._h else 0

    def num_objects(self) -> int:
        return self._lib.rt_store_num_objects(self._h) if self._h else 0

    def evictions(self) -> int:
        return self._lib.rt_store_evictions(self._h) if self._h else 0

    def close(self, unlink: bool = False):
        if self._h:
            try:
                self._view.release()
                self._map.close()
            except (BufferError, ValueError):
                pass
            self._lib.rt_store_close(self._h)
            if unlink:
                self._lib.rt_store_unlink(self.path.encode())
            self._h = None


class TransferServer:
    """Serves this node's arena to peers (reference: ObjectManager server
    side)."""

    def __init__(self, store: NativeStore, port: int = 0):
        self._lib = store._lib
        self._h = self._lib.rt_transfer_serve(store._h, port)
        if not self._h:
            raise RuntimeError("failed to start transfer server")
        self.port = self._lib.rt_transfer_port(self._h)

    def stop(self):
        if self._h:
            self._lib.rt_transfer_stop(self._h)
            self._h = None


def pull(local: NativeStore, host: str, port: int, object_id) -> None:
    """Pull one object from a peer into the local arena (reference:
    PullManager)."""
    rc = local._lib.rt_transfer_pull(
        local._h, host.encode(), port, NativeStore._key(object_id))
    if rc == -2:
        raise KeyError("object not on remote")
    if rc != 0:
        raise RuntimeError(f"pull failed (rc={rc})")
