"""Declarative Serve config: build, validate, and deploy from YAML/dicts.

Reference parity: python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema — the pydantic models behind
`serve build` and `serve deploy config.yaml`) and serve/scripts.py (the
CLI that round-trips them). Here the schemas are validating dataclasses:
same YAML shape, no pydantic dependency.

    applications:
      - name: default
        import_path: my_module:app
        route_prefix: /
        deployments:
          - name: Model
            num_replicas: 2
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class SchemaError(ValueError):
    pass


def _expect(cond: bool, msg: str):
    if not cond:
        raise SchemaError(msg)


@dataclass
class DeploymentSchema:
    """Per-deployment overrides (reference: schema.py DeploymentSchema)."""

    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    user_config: Optional[Dict[str, Any]] = None
    ray_actor_options: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        _expect(isinstance(d, dict), "deployment entry must be a mapping")
        _expect("name" in d, "deployment entry needs a `name`")
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        _expect(not unknown,
                f"unknown deployment fields {sorted(unknown)} "
                f"(known: {sorted(known)})")
        if d.get("num_replicas") is not None:
            _expect(int(d["num_replicas"]) >= 0,
                    "num_replicas must be >= 0")
        return cls(**d)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if v is not None}


@dataclass
class ServeApplicationSchema:
    """One application (reference: schema.py ServeApplicationSchema)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = "/"
    args: Dict[str, Any] = field(default_factory=dict)
    runtime_env: Optional[Dict[str, Any]] = None
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        _expect(isinstance(d, dict), "application entry must be a mapping")
        _expect("import_path" in d,
                "application entry needs an `import_path` "
                "(format: module.sub:attribute)")
        path = d["import_path"]
        _expect(isinstance(path, str) and ":" in path,
                f"import_path {path!r} must look like 'module:attribute'")
        rp = d.get("route_prefix", "/")
        if rp is not None:
            _expect(str(rp).startswith("/"),
                    f"route_prefix {rp!r} must start with '/'")
        deps = [DeploymentSchema.from_dict(x)
                for x in d.get("deployments", [])]
        return cls(import_path=path, name=d.get("name", "default"),
                   route_prefix=rp, args=d.get("args", {}) or {},
                   runtime_env=d.get("runtime_env"), deployments=deps)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name,
                               "import_path": self.import_path,
                               "route_prefix": self.route_prefix}
        if self.args:
            out["args"] = self.args
        if self.runtime_env:
            out["runtime_env"] = self.runtime_env
        if self.deployments:
            out["deployments"] = [x.to_dict() for x in self.deployments]
        return out


@dataclass
class ServeDeploySchema:
    """Top-level config (reference: schema.py ServeDeploySchema)."""

    applications: List[ServeApplicationSchema]
    http_options: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeDeploySchema":
        _expect(isinstance(d, dict), "serve config must be a mapping")
        apps = d.get("applications")
        _expect(isinstance(apps, list) and apps,
                "serve config needs a non-empty `applications` list")
        parsed = [ServeApplicationSchema.from_dict(a) for a in apps]
        names = [a.name for a in parsed]
        _expect(len(set(names)) == len(names),
                f"duplicate application names: {names}")
        prefixes = [a.route_prefix for a in parsed
                    if a.route_prefix is not None]
        _expect(len(set(prefixes)) == len(prefixes),
                f"duplicate route prefixes: {prefixes}")
        return cls(applications=parsed,
                   http_options=d.get("http_options"))

    @classmethod
    def from_yaml(cls, path: str) -> "ServeDeploySchema":
        import yaml
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "applications": [a.to_dict() for a in self.applications]}
        if self.http_options:
            out["http_options"] = self.http_options
        return out


def import_attr(import_path: str):
    """'module.sub:attr' → the attribute (reference:
    ray._private.utils.import_attr, used by serve deploy)."""
    module_path, _, attr = import_path.partition(":")
    mod = importlib.import_module(module_path)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def build_app(schema: ServeApplicationSchema):
    """Materialize one application: import it, apply per-deployment
    overrides (reference: serve/_private/api.py build_app)."""
    import copy

    from . import Application
    target = import_attr(schema.import_path)
    app = target(**schema.args) if callable(target) \
        and not isinstance(target, Application) else target
    _expect(isinstance(app, Application),
            f"{schema.import_path} must resolve to a bound Serve "
            f"Application (call .bind()), got {type(app).__name__}")
    # Never mutate the module-level (sys.modules-cached) Application:
    # overrides applied in place would leak into every later deploy of
    # the same import_path in this process.
    app = copy.deepcopy(app)
    if schema.deployments:
        from . import _collect_deployments
        found: Dict[str, Any] = {}
        _collect_deployments(app, found)
        overrides = {d.name: d for d in schema.deployments}
        unknown = set(overrides) - set(found)
        _expect(not unknown,
                f"config overrides unknown deployments {sorted(unknown)} "
                f"(app has {sorted(found)})")
        for name, sub_app in found.items():
            ov = overrides.get(name)
            if ov is None:
                continue
            dep = sub_app.deployment
            opts: Dict[str, Any] = {}
            if ov.num_replicas is not None:
                opts["num_replicas"] = ov.num_replicas
            if ov.max_ongoing_requests is not None:
                opts["max_ongoing_requests"] = ov.max_ongoing_requests
            if ov.autoscaling_config is not None:
                from .config import AutoscalingConfig
                opts["autoscaling_config"] = AutoscalingConfig(
                    **ov.autoscaling_config)
            if ov.ray_actor_options is not None:
                opts["ray_actor_options"] = ov.ray_actor_options
            if ov.user_config is not None:
                opts["user_config"] = ov.user_config
            if opts:
                sub_app.deployment = dep.options(**opts)
    if schema.runtime_env:
        import warnings
        warnings.warn(
            f"application {schema.name!r}: runtime_env in serve configs "
            "is not applied by this build — replicas inherit the "
            "cluster's environment. Set the env before `ray_tpu start`.",
            stacklevel=2)
    return app


def deploy_config(schema: ServeDeploySchema) -> List[str]:
    """Deploy every application in the config (reference: `serve deploy`
    handled by the controller's deploy_apps). Returns deployed names."""
    from . import HTTPOptions, run
    http = None
    if schema.http_options:
        http = HTTPOptions(**schema.http_options)
    names = []
    for app_schema in schema.applications:
        app = build_app(app_schema)
        run(app, name=app_schema.name,
            route_prefix=app_schema.route_prefix,
            http_options=http)
        names.append(app_schema.name)
    return names


def build_config(app, name: str = "default", import_path: str = "",
                 route_prefix: str = "/") -> Dict[str, Any]:
    """Emit the YAML-able config for a bound application (reference:
    `serve build`). Pass the deploy-time `route_prefix` so a
    build→deploy round trip preserves it."""
    from . import _collect_deployments
    found: Dict[str, Any] = {}
    _collect_deployments(app, found)
    deployments = []
    for dep_name, sub_app in sorted(found.items()):
        cfg = sub_app.deployment.config
        entry = {
            "name": dep_name,
            "num_replicas": cfg.num_replicas,
            "max_ongoing_requests": cfg.max_ongoing_requests,
        }
        if getattr(cfg, "user_config", None) is not None:
            entry["user_config"] = cfg.user_config
        deployments.append(entry)
    return {"applications": [{
        "name": name,
        "import_path": import_path or "module:app  # EDIT ME",
        "route_prefix": route_prefix,
        "deployments": deployments,
    }]}
