"""Serve configuration objects.

Reference parity: python/ray/serve/config.py (DeploymentConfig,
AutoscalingConfig, HTTPOptions) — re-designed for TPU replicas: a
deployment's `ray_actor_options` may reserve TPU chips, and batching
(batching.py) pads to fixed bucket shapes so each replica's jitted model
compiles once per bucket instead of once per request shape.
"""
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Reference: serve/config.py AutoscalingConfig +
    serve/_private/autoscaling_policy.py (replica-count policy)."""
    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0
    look_back_period_s: float = 5.0

    def desired_replicas(self, total_ongoing: float, current: int) -> int:
        if current == 0:
            return max(self.min_replicas, 1)
        want = total_ongoing / max(self.target_ongoing_requests, 1e-9)
        import math
        want = int(math.ceil(want))
        return max(self.min_replicas, min(self.max_replicas, want))


@dataclass
class DeploymentConfig:
    """Reference: serve/config.py DeploymentConfig."""
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 10.0
    user_config: Optional[Any] = None
    graceful_shutdown_timeout_s: float = 5.0

    @property
    def initial_replicas(self) -> int:
        if self.autoscaling_config is not None:
            return self.autoscaling_config.min_replicas
        return self.num_replicas


@dataclass
class HTTPOptions:
    """Reference: serve/config.py HTTPOptions."""
    host: str = "127.0.0.1"
    port: int = 8000
