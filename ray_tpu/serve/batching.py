"""@serve.batch — dynamic request batching.

Reference: python/ray/serve/batching.py (@serve.batch decorator). On TPU
this is the load-bearing inference feature: individual requests are
queued and flushed as one batch into the wrapped method, so the replica's
`jax.jit` model sees a small set of padded bucket sizes (powers of two up
to max_batch_size) and compiles once per bucket instead of once per
request count — recompilation is the classic XLA serving footgun.
"""
import asyncio
import functools
from typing import Any, Callable, List, Optional


def _bucket(n: int, max_batch_size: int) -> int:
    """Next power-of-two bucket ≥ n (≤ max_batch_size)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch_size)


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._queue: List = []           # (item, future)
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, instance, item: Any) -> Any:
        fut = asyncio.get_event_loop().create_future()
        self._queue.append((item, fut))
        if len(self._queue) >= self._max:
            await self._flush(instance)
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_event_loop().create_task(
                self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self._wait)
        await self._flush(instance)

    async def _flush(self, instance):
        if not self._queue:
            return
        batch, self._queue = self._queue, []
        items = [b[0] for b in batch]
        try:
            if instance is not None:
                outs = self._fn(instance, items)
            else:
                outs = self._fn(items)
            if asyncio.iscoroutine(outs):
                outs = await outs
            if len(outs) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(outs)} results "
                    f"for a batch of {len(items)}")
            for (_, fut), out in zip(batch, outs):
                if not fut.done():
                    fut.set_result(out)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorate an async method taking List[item] -> List[result]; callers
    invoke it with single items (reference: serve/batching.py)."""

    def deco(fn):
        queues = {}  # per-instance (or None for free functions)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                instance, item = args
            elif len(args) == 1:
                instance, item = None, args[0]
            else:
                raise TypeError("@serve.batch methods take one argument")
            key = id(instance)
            q = queues.get(key)
            if q is None:
                q = queues[key] = _BatchQueue(
                    fn, max_batch_size, batch_wait_timeout_s)
            return await q.submit(instance, item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco


def pad_batch_to_bucket(arrays, max_batch_size: int, pad_value=0):
    """Stack a list of equal-shape arrays into one batch padded to the next
    power-of-two bucket — the jit-cache-friendly shape policy. Returns
    (batched_array, real_count)."""
    import numpy as np
    n = len(arrays)
    b = _bucket(n, max_batch_size)
    stacked = np.stack(arrays)
    if b > n:
        pad = np.full((b - n,) + stacked.shape[1:], pad_value,
                      dtype=stacked.dtype)
        stacked = np.concatenate([stacked, pad])
    return stacked, n
