"""ray_tpu.serve — online inference serving.

Reference parity: python/ray/serve/api.py (serve.deployment :246,
serve.run, serve.start, serve.delete, serve.status) over the TPU-native
control plane: a controller actor reconciles replica actors
(_private/controller.py), routers do power-of-two-choices scheduling
(handle.py), @serve.batch pads request batches into XLA-friendly bucket
shapes (batching.py), and an HTTP proxy fronts applications
(_private/proxy.py).

    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, x):
            return self.predict(x)

    handle = serve.run(Model.bind())
    handle.remote({"x": 1}).result()
"""
import inspect
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from .asgi import ingress
from .config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from .handle import DeploymentHandle, DeploymentResponse
from .batching import batch, pad_batch_to_bucket
from .multiplex import get_multiplexed_model_id, multiplexed

_proxy = None  # module-level HTTP proxy singleton (per driver process)


class Application:
    """A bound deployment DAG node (reference: serve/api.py Application /
    dag build via .bind)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    """Reference: serve/deployment.py Deployment."""

    def __init__(self, target: Union[type, Callable], name: str,
                 config: DeploymentConfig):
        self._target = target
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                ray_actor_options: Optional[Dict] = None,
                user_config: Optional[Any] = None,
                health_check_period_s: Optional[float] = None,
                health_check_timeout_s: Optional[float] = None) -> "Deployment":
        import copy
        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if autoscaling_config is not None:
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if user_config is not None:
            cfg.user_config = user_config
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if health_check_timeout_s is not None:
            cfg.health_check_timeout_s = health_check_timeout_s
        return Deployment(self._target, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise RuntimeError(
            f"Deployment {self.name} cannot be called directly; deploy via "
            "serve.run(deployment.bind(...)) and call the handle.")


def deployment(_target=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 8,
               autoscaling_config: Optional[Union[AutoscalingConfig,
                                                  Dict]] = None,
               ray_actor_options: Optional[Dict] = None,
               user_config: Optional[Any] = None,
               health_check_period_s: float = 2.0,
               health_check_timeout_s: float = 10.0):
    """@serve.deployment decorator (reference: serve/api.py:246)."""
    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)

    def deco(target):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            ray_actor_options=dict(ray_actor_options or {}),
            user_config=user_config,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s)
        return Deployment(target, name or target.__name__, cfg)

    if _target is not None:
        return deco(_target)
    return deco


# ---------------------------------------------------------------------------
# deploy / run
# ---------------------------------------------------------------------------
def _collect_deployments(app: Application, out: Dict[str, Application]):
    """DFS over the bound DAG: nested Applications become handle args."""
    for a in list(app.args) + list(app.kwargs.values()):
        if isinstance(a, Application):
            _collect_deployments(a, out)
    existing = out.get(app.deployment.name)
    if existing is not None and existing.deployment._target \
            is not app.deployment._target:
        raise ValueError(
            f"Two different deployments named '{app.deployment.name}'")
    out[app.deployment.name] = app


def _to_controller_spec(app: Application, app_name: str) -> Dict[str, Any]:
    import cloudpickle
    d = app.deployment

    def _sub(a):
        if isinstance(a, Application):
            return DeploymentHandle(a.deployment.name, app_name)
        return a

    args = tuple(_sub(a) for a in app.args)
    kwargs = {k: _sub(v) for k, v in app.kwargs.items()}
    cfg = d.config
    return {
        "name": d.name,
        "cls_blob": cloudpickle.dumps(d._target),
        "init_args": args,
        "init_kwargs": kwargs,
        "actor_options": dict(cfg.ray_actor_options),
        "max_ongoing_requests": cfg.max_ongoing_requests,
        "autoscaling_config": cfg.autoscaling_config,
        "user_config": cfg.user_config,
        "initial_replicas": cfg.initial_replicas,
        "health_check_period_s": cfg.health_check_period_s,
        "health_check_timeout_s": cfg.health_check_timeout_s,
    }


def start(http_options: Optional[HTTPOptions] = None, *,
          detached: bool = True):
    """Start Serve (controller + HTTP proxy) without deploying an app
    (reference: serve/api.py serve.start)."""
    global _proxy
    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    from ._private.controller import get_controller
    controller = get_controller()
    if _proxy is None and http_options is not False:
        from ._private.proxy import HTTPProxy
        opts = http_options or HTTPOptions(port=0)
        _proxy = HTTPProxy(controller, opts.host, opts.port)
        # Multi-host data plane: the controller keeps one proxy actor on
        # every non-head node (reference: proxy_state.py EveryNode
        # location default); the in-driver proxy above covers the head.
        # The configured host applies verbatim to every proxy — the
        # loopback default stays loopback (pass
        # HTTPOptions(host="0.0.0.0") to expose ingress off-host).
        try:
            ray_tpu.get(controller.configure_proxies.remote(
                opts.host, opts.port), timeout=30)
        except Exception:
            pass
    return controller


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/",
        http_options: Optional[HTTPOptions] = None,
        _blocking: bool = True) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment
    (reference: serve/api.py serve.run)."""
    controller = start(http_options)
    apps: Dict[str, Application] = {}
    _collect_deployments(app, apps)
    specs = [_to_controller_spec(a, name) for a in apps.values()]
    ingress = app.deployment.name
    ray_tpu.get(controller.deploy_application.remote(
        name, specs, route_prefix, ingress))
    return DeploymentHandle(ingress, name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    from ._private.controller import get_controller
    controller = get_controller()
    routes = ray_tpu.get(controller.get_route_table.remote())
    for _prefix, (app, ingress) in routes.items():
        if app == name:
            return DeploymentHandle(ingress, app)
    deps = ray_tpu.get(controller.list_deployments.remote())
    for dep, info in deps.items():
        if info.get("app") == name:
            return DeploymentHandle(dep, name)
    raise ValueError(f"No application named '{name}'")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> Dict[str, Any]:
    """Reference: serve/api.py serve.status."""
    from ._private.controller import get_controller
    return ray_tpu.get(get_controller().list_deployments.remote())


def delete(name: str):
    from ._private.controller import get_controller
    ray_tpu.get(get_controller().delete_application.remote(name))


def start_grpc(host: str = "127.0.0.1", port: int = 0):
    """Start the gRPC ingress next to the HTTP proxy (reference:
    serve.start(grpc_options=...) → gRPC proxy). Returns the proxy;
    `proxy.port` is the bound port. See
    `_private/grpc_proxy.GrpcServeClient` for the matching client."""
    start()  # ensure controller up
    from ._private.grpc_proxy import start_grpc_proxy
    return start_grpc_proxy(host, port)


def shutdown():
    """Tear down all applications, the controller, and the proxies."""
    global _proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
    try:
        from ._private.grpc_proxy import stop_grpc_proxy
        stop_grpc_proxy()
    except Exception:
        pass
    try:
        # Close the direct serve channels: replica workers are about to
        # die, and their EOFs must not fan typed errors into the NEXT
        # cluster this process starts.
        from ._private.direct_client import reset_client
        reset_client()
    except Exception:
        pass
    try:
        from ._private.controller import CONTROLLER_NAME
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.graceful_shutdown.remote())
        ray_tpu.kill(controller)
    except Exception:
        pass


def proxy_address() -> Optional[str]:
    return f"http://{_proxy.host}:{_proxy.port}" if _proxy else None


def proxy_addresses() -> Dict[str, str]:
    """Every node's ingress URL: the driver proxy plus the controller's
    per-node proxy actors (reference: proxy locations in serve.status)."""
    out: Dict[str, str] = {}
    if _proxy is not None:
        out["_driver"] = f"http://{_proxy.host}:{_proxy.port}"
    try:
        from ._private.controller import get_controller
        table = ray_tpu.get(
            get_controller().get_proxy_table.remote(), timeout=10)
        for node_hex, (host, port) in table.items():
            # The controller already resolved 0.0.0.0 binds to the
            # node's registered peer IP; loopback remains only for
            # single-machine clusters, where it IS the right address.
            shown = "127.0.0.1" if host in ("0.0.0.0", "::") else host
            out[node_hex] = f"http://{shown}:{port}"
    except Exception:
        pass
    return out


__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentConfig",
    "DeploymentHandle", "DeploymentResponse", "HTTPOptions", "batch",
    "delete", "deployment", "get_app_handle", "get_deployment_handle",
    "get_multiplexed_model_id", "ingress", "multiplexed",
    "pad_batch_to_bucket", "proxy_address", "proxy_addresses", "run", "shutdown", "start", "start_grpc",
    "status",
]
