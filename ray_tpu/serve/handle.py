"""DeploymentHandle: the client-side request path.

Reference: python/ray/serve/handle.py (DeploymentHandle/DeploymentResponse)
+ serve/_private/router.py:321,578 (Router.assign_request) +
replica_scheduler/pow_2_scheduler.py:52 (PowerOfTwoChoicesReplicaScheduler).

The router keeps a local in-flight count per replica (decremented via the
object-ref done callback) and samples two replicas per request, routing to
the less loaded — the power-of-two-choices policy. Replica membership is
pushed by the controller over long-poll, so the data path never blocks on
the control plane.
"""
import random
import threading
from typing import Any, Dict, List, Optional

import ray_tpu
from .._private import state as _state
from ._private.long_poll import LongPollClient


class DeploymentResponse:
    """Future-like result of handle.remote() (reference: handle.py
    DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def _to_object_ref(self):
        return self._ref

    def __await__(self):
        return self._ref.__await__()


class DeploymentResponseGenerator:
    """Streamed result of handle.options(stream=True).remote()
    (reference: handle.py DeploymentResponseGenerator). Iterates the
    user generator's items as values; the leading replica marker dict is
    consumed internally — `is_stream` tells whether the user callable
    actually returned a generator (False: `single_result()` holds its
    one return value)."""

    def __init__(self, ref_gen):
        self._gen = ref_gen
        self._marker: Optional[dict] = None

    def _read_marker(self, timeout_s: Optional[float] = None) -> dict:
        if self._marker is None:
            self._marker = ray_tpu.get(
                self._gen.next_ready(timeout=timeout_s))
        return self._marker

    def is_stream(self, timeout_s: Optional[float] = None) -> bool:
        """Did the user callable return a generator? (The proxy uses
        this to pick chunked vs plain responses.)"""
        return bool(self._read_marker(timeout_s).get("__stream__"))

    def single_result(self, timeout_s: Optional[float] = None) -> Any:
        """The one value of a non-stream response."""
        self._read_marker(timeout_s)
        return ray_tpu.get(self._gen.next_ready(timeout=timeout_s))

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        self._read_marker()
        return ray_tpu.get(next(self._gen))


class _Router:
    """Pow-2 replica scheduler over the current replica set."""

    def __init__(self, deployment_name: str, controller):
        self._deployment = deployment_name
        self._lock = threading.Lock()
        self._replicas: List = []
        self._inflight: Dict[int, int] = {}
        self._ready = threading.Event()
        self._long_poll = LongPollClient(
            controller,
            {f"replicas::{deployment_name}": self._update_replicas})
        # Seed synchronously so the first request doesn't wait a poll cycle.
        try:
            snap = ray_tpu.get(
                controller.get_replica_snapshot.remote(deployment_name))
            if snap:
                self._update_replicas(snap)
        except Exception:
            pass

    def _update_replicas(self, replicas: List):
        with self._lock:
            self._replicas = list(replicas)
            self._inflight = {i: self._inflight.get(i, 0)
                              for i in range(len(self._replicas))}
            self._qlen_base = {}
            self._qlen_ts = {}
            # model id -> replica indices known to hold it (refreshed by
            # probes; indices are positions in THIS replica list, so a
            # membership change invalidates everything).
            self._model_locations = {}
            self._model_note_ts = {}
        if self._replicas:
            self._ready.set()
        else:
            self._ready.clear()

    _PROBE_TTL_S = 0.1
    # Queue-length gap beyond which a multiplexed request abandons its
    # warm replica and spills (the new replica pays one model load).
    _MUX_SPILL_QLEN = 8
    # Optimistic model-location notes survive probes this long: a model
    # load (weights into HBM) can take seconds, and wiping the note on
    # the first pre-load probe would fan concurrent same-model requests
    # across replicas, each paying a duplicate load.
    _MUX_NOTE_GRACE_S = 30.0

    def _replica_score(self, idx: int, now: float) -> float:
        """Replica load = last probed queue length + requests THIS router
        sent since the probe (reference: pow_2_scheduler.py:52 replica
        queue-length probes with caching). The probe sees ALL routers'
        traffic, which router-local inflight counts alone cannot."""
        base = getattr(self, "_qlen_base", {}).get(idx)
        if base is None:
            return float(self._inflight.get(idx, 0))
        return base + self._inflight.get(idx, 0)

    def _maybe_probe(self, candidates: List[int]):
        """Refresh stale queue-length probes for the sampled candidates
        (outside the lock; one RPC pair at most every _PROBE_TTL_S)."""
        import time as _time
        now = _time.monotonic()
        with self._lock:
            stale = [i for i in candidates
                     if now - getattr(self, "_qlen_ts", {}).get(i, 0.0)
                     > self._PROBE_TTL_S]
            reps = {i: self._replicas[i] for i in stale
                    if i < len(self._replicas)}
            for i in stale:
                # Mark probed first: concurrent requests must not stampede
                # the same replica with probe RPCs while ours is in flight.
                self._qlen_ts.setdefault(i, 0.0)
                self._qlen_ts[i] = now
        if not reps:
            return
        refs = {i: r.get_queue_len_and_models.remote()
                for i, r in reps.items()}
        try:
            probes = ray_tpu.get(list(refs.values()), timeout=2.0)
        except Exception:
            return  # unreachable replica(s): fall back to local counts
        for i, (qlen, model_ids) in zip(refs, probes):
            with self._lock:
                if i in self._inflight:
                    # Probe reflects work in flight cluster-wide NOW;
                    # future local sends add on top.
                    self._qlen_base = getattr(self, "_qlen_base", {})
                    self._qlen_base[i] = float(qlen) - self._inflight.get(
                        i, 0)
                locs = getattr(self, "_model_locations", None)
                if locs is None:
                    locs = self._model_locations = {}
                notes = getattr(self, "_model_note_ts", None)
                if notes is None:
                    notes = self._model_note_ts = {}
                for m in list(locs):
                    if m in model_ids:
                        continue
                    # Keep optimistic notes young enough that the load
                    # may still be in flight; trust the probe otherwise.
                    if now - notes.get((m, i), -1e9) < \
                            self._MUX_NOTE_GRACE_S:
                        continue
                    locs[m].discard(i)
                for m in model_ids:
                    locs.setdefault(m, set()).add(i)
                    # Confirmed on-replica: future absence means a real
                    # eviction, so the optimistic note must not linger.
                    notes.pop((m, i), None)
        with self._lock:
            # Bounded state (once per probe round, not per replica):
            # expired notes and emptied location sets are dead weight
            # on long-lived routers with churning model ids.
            notes = getattr(self, "_model_note_ts", {})
            locs = getattr(self, "_model_locations", {})
            for key_ in [k for k, ts in notes.items()
                         if now - ts >= self._MUX_NOTE_GRACE_S]:
                notes.pop(key_, None)
            for m in [m for m, s_ in locs.items() if not s_]:
                locs.pop(m, None)

    def _pick(self, candidates: Optional[List[int]] = None,
              model_id: str = "") -> int:
        import time as _time
        n = len(self._replicas)
        if n == 1:
            return 0
        now = _time.monotonic()
        if candidates:
            a, b = candidates
        elif n == 2:
            a, b = 0, 1  # the common 2-replica case: sampling is noise
        else:
            a, b = random.sample(range(n), 2)
        fallback = a if self._replica_score(a, now) <= \
            self._replica_score(b, now) else b
        if model_id:
            # Model-aware ranking (reference: pow_2_scheduler's
            # multiplexed preference): pow-2 among replicas that already
            # hold the model — but SPILL to the plain pow-2 pick when
            # the holders are loaded well past it, so one hot model
            # scales onto idle replicas (which then load it) instead of
            # pinning to a saturated one.
            locs = getattr(self, "_model_locations", {}).get(model_id)
            holders = [i for i in (locs or ()) if i < n]
            if holders:
                if len(holders) > 2:
                    holders = random.sample(holders, 2)
                best = min(holders,
                           key=lambda i: self._replica_score(i, now))
                if self._replica_score(best, now) < \
                        self._replica_score(fallback, now) + \
                        self._MUX_SPILL_QLEN:
                    return best
        return fallback

    def _probe_stale(self, candidates: List[int], now: float) -> bool:
        """Caller holds self._lock."""
        return any(now - getattr(self, "_qlen_ts", {}).get(i, 0.0)
                   > self._PROBE_TTL_S for i in candidates)

    def _submit_to(self, idx: int, replica, method_name: str,
                   args: tuple, kwargs: dict, model_id: str = ""):
        """Submit a unary call to a picked replica, with the in-flight
        decrement wired to completion (shared by the blocking and
        event-loop fast paths — the bookkeeping must never diverge)."""
        ref = replica.handle_request.remote(method_name, args, kwargs,
                                            model_id)

        def _done():
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
        try:
            # Readiness callback straight off the object directory: the
            # decrement needs no value, so building a concurrent.Future
            # + resolver-pool get() per request (the .future() path)
            # would be pure overhead on the serve hot path. Worker
            # processes (deployment composition: a replica holding a
            # handle) have no object directory — fall back to the
            # future-based path there rather than silently never
            # decrementing.
            from ray_tpu._private import state as _state
            objects = getattr(getattr(_state.current(), "gcs", None),
                              "objects", None)
            if objects is not None:
                objects.add_ready_callback(ref.id, _done)
            else:
                ref.future().add_done_callback(lambda _f: _done())
        except Exception:
            pass
        return ref

    def _note_model_location(self, model_id: str, idx: int):
        """Caller holds self._lock. Optimistic: the replica we just sent
        model_id to will have it loaded by the time the next probe runs;
        the note timestamp shields it from probe wipes for
        _MUX_NOTE_GRACE_S while the load is in flight."""
        if model_id:
            import time as _time
            locs = getattr(self, "_model_locations", None)
            if locs is None:
                locs = self._model_locations = {}
            locs.setdefault(model_id, set()).add(idx)
            notes = getattr(self, "_model_note_ts", None)
            if notes is None:
                notes = self._model_note_ts = {}
            notes[(model_id, idx)] = _time.monotonic()

    def try_assign_fast(self, method_name: str, args: tuple,
                        kwargs: dict, model_id: str = ""):
        """Non-blocking assignment for callers that must not stall an
        event loop (the async proxy): succeeds only when replicas are
        ready AND the sampled candidates' queue-length probes are fresh
        — anything that could block (ready-wait, probe RPC) returns
        None and the caller falls back to an executor thread."""
        if not self._ready.is_set():
            return None
        import time as _time
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                return None
            if n > 1:
                candidates = random.sample(range(n), 2)
                if self._probe_stale(candidates, _time.monotonic()):
                    return None  # probe due: take the blocking path
                idx = self._pick(candidates, model_id)
            else:
                idx = 0
            replica = self._replicas[idx]
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            self._note_model_location(model_id, idx)
        return self._submit_to(idx, replica, method_name, args, kwargs,
                               model_id)

    def _pick_and_claim(self, model_id: str, timeout_s: float):
        """Shared pow-2 selection + in-flight claim (used by
        assign_request and pick_sticky): ready-wait, sample+probe,
        stale-candidate revalidation, pick, increment. Returns
        (idx, replica)."""
        if not self._ready.wait(timeout=timeout_s):
            raise TimeoutError(
                f"No replicas of '{self._deployment}' became available "
                f"within {timeout_s}s")
        with self._lock:
            n = len(self._replicas)
        candidates = random.sample(range(n), 2) if n > 1 else None
        if candidates is not None:
            probe_set = list(candidates)
            if model_id:
                # Holders of the model compete against the sampled
                # candidates in _pick's warm-vs-spill comparison, so
                # their queue lengths must be comparably fresh — a
                # holder probed only at load time would keep a stale
                # (often zero) score and soak every request.
                with self._lock:
                    locs = getattr(self, "_model_locations", {}).get(
                        model_id, ())
                    probe_set.extend(i for i in locs
                                     if i < n and i not in probe_set)
            self._maybe_probe(probe_set)
        with self._lock:
            if candidates is not None and any(
                    i >= len(self._replicas) for i in candidates):
                candidates = None  # replica set changed under us
            idx = self._pick(candidates, model_id)
            replica = self._replicas[idx]
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            self._note_model_location(model_id, idx)
        return idx, replica

    def try_claim_direct(self, model_id: str = ""):
        """Non-blocking claim for the direct serve data plane: pick the
        LEAST-LOADED replica across the whole set (a channel hop is too
        cheap for pow-2 sampling to pay for itself here, and the full
        scan is what makes the shed decision exact), increment its
        in-flight count, and return (idx, replica, release). Returns
        None when replicas aren't ready (the caller falls back to the
        classic path); raises ReplicaQueueFullError when EVERY
        replica's proxy-tracked queue is at serve_max_queue_per_replica
        — backpressure at the edge instead of a wedged replica pool."""
        if not self._ready.is_set():
            return None
        import time as _time

        from ray_tpu._private.config import ray_config
        from ._private.direct_client import ReplicaQueueFullError
        cap = int(ray_config.serve_max_queue_per_replica)
        now = _time.monotonic()
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                return None
            if cap > 0 and all(self._inflight.get(i, 0) >= cap
                               for i in range(n)):
                raise ReplicaQueueFullError(
                    f"all {n} replica(s) of '{self._deployment}' have "
                    f">= {cap} requests in flight")
            idx = min(range(n),
                      key=lambda i: self._replica_score(i, now))
            if model_id:
                # Model-aware preference with the same spill rule as
                # _pick: a warm holder wins until it is loaded well
                # past the least-loaded replica.
                locs = getattr(self, "_model_locations", {}).get(
                    model_id)
                holders = [i for i in (locs or ()) if i < n]
                if holders:
                    best = min(holders, key=lambda i:
                               self._replica_score(i, now))
                    if self._replica_score(best, now) < \
                            self._replica_score(idx, now) + \
                            self._MUX_SPILL_QLEN:
                        idx = best
            if cap > 0 and self._inflight.get(idx, 0) >= cap:
                # Probe-biased scores can land on a replica already at
                # cap while another sits below it (the all() check
                # above guarantees one exists): spill to the least
                # raw-inflight replica.
                idx = min(range(n),
                          key=lambda i: self._inflight.get(i, 0))
            replica = self._replicas[idx]
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            self._note_model_location(model_id, idx)
        released = []

        def release():
            with self._lock:
                if released:
                    return
                released.append(True)
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
        return idx, replica, release

    def total_inflight(self) -> int:
        """Proxy-tracked in-flight requests across all replicas (the
        queue-depth gauge's source)."""
        with self._lock:
            return sum(self._inflight.values())

    def pick_sticky(self, timeout_s: float = 30.0):
        """Pick ONE replica for a long-lived connection (websockets):
        returns (replica_actor, release). The connection counts as
        in-flight load until `release()` so the pow-2 chooser steers
        short requests away from replicas holding many sockets
        (reference: the proxy pins a websocket to one replica for the
        connection's lifetime, serve/_private/proxy.py:418)."""
        idx, replica = self._pick_and_claim("", timeout_s)
        released = threading.Event()

        def release():
            if released.is_set():
                return
            released.set()
            with self._lock:
                if idx in self._inflight and self._inflight[idx] > 0:
                    self._inflight[idx] -= 1
        return replica, release

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       timeout_s: float = 30.0, stream: bool = False,
                       model_id: str = ""):
        idx, replica = self._pick_and_claim(model_id, timeout_s)
        if stream:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming").remote(method_name, args, kwargs,
                                                model_id)

            def _stream_done():
                with self._lock:
                    if idx in self._inflight and self._inflight[idx] > 0:
                        self._inflight[idx] -= 1
            try:
                gen.add_done_callback(_stream_done)
            except Exception:
                _stream_done()
            return gen
        return self._submit_to(idx, replica, method_name, args, kwargs,
                               model_id)

    def shutdown(self):
        self._long_poll.stop()


class DeploymentHandle:
    """Callable handle to a deployment (reference: handle.py:~200).

    Picklable: reconnects to the named controller actor on deserialize, so
    handles can be passed into other replicas for model composition.
    """

    def __init__(self, deployment_name: str, app_name: str = "default",
                 method_name: str = "__call__", stream: bool = False,
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method = method_name
        self._stream = stream
        self._model_id = multiplexed_model_id
        # Router cell SHARED by every options() copy: whichever handle
        # routes first builds the router, all copies reuse it (and its
        # probe caches / model-location map). A per-copy router would
        # leak a long-poll thread per options() call.
        self._router_cell: Dict[str, Optional[_Router]] = {"router": None}
        self._lock = threading.Lock()

    @property
    def _router(self) -> Optional[_Router]:
        return self._router_cell["router"]

    # -- pickling ----------------------------------------------------------
    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method,
                 self._stream, self._model_id))

    # -- routing -----------------------------------------------------------
    def _get_router(self) -> _Router:
        with self._lock:
            if self._router_cell["router"] is None:
                from ._private.controller import get_controller
                self._router_cell["router"] = _Router(
                    self.deployment_name, get_controller())
            return self._router_cell["router"]

    def options(self, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name or self._method,
            self._stream if stream is None else stream,
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id)
        # Copies share the router cell AND its build lock, so exactly
        # one router (one long-poll client) exists per handle family.
        h._router_cell = self._router_cell
        h._lock = self._lock
        return h

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    @staticmethod
    def _unwrap(args, kwargs):
        """DeploymentResponse args become their underlying refs
        (model-composition chaining) — shared by both submit paths."""
        return (tuple(a._to_object_ref()
                      if isinstance(a, DeploymentResponse) else a
                      for a in args),
                {k: (v._to_object_ref()
                     if isinstance(v, DeploymentResponse) else v)
                 for k, v in kwargs.items()})

    def remote(self, *args, **kwargs):
        args, kwargs = self._unwrap(args, kwargs)
        out = self._get_router().assign_request(
            self._method, args, kwargs, stream=self._stream,
            model_id=self._model_id)
        if self._stream:
            return DeploymentResponseGenerator(out)
        return DeploymentResponse(out)

    def _remote_fast(self, *args, **kwargs):
        """Event-loop-safe submission: DeploymentResponse, or None when
        assignment would block (proxy falls back to an executor).
        Router CONSTRUCTION blocks (controller lookup + replica
        snapshot), so an unbuilt router also means None."""
        if self._stream:
            return None
        with self._lock:
            router = self._router
        if router is None:
            return None
        args, kwargs = self._unwrap(args, kwargs)
        ref = router.try_assign_fast(self._method, args, kwargs,
                                     model_id=self._model_id)
        return DeploymentResponse(ref) if ref is not None else None

    def shutdown(self):
        with self._lock:
            router = self._router_cell["router"]
            if router is not None:
                router.shutdown()
                self._router_cell["router"] = None
