"""Model multiplexing: many models per deployment, LRU-cached per replica.

Reference parity: python/ray/serve/api.py `@serve.multiplexed` +
`serve.get_multiplexed_model_id()` and
python/ray/serve/multiplexed.py (_ModelMultiplexWrapper: per-replica LRU
of models keyed by model id, loaded through the user's decorated
loader). Routers prefer replicas that already hold the requested model
(model-aware power-of-two, reference:
replica_scheduler/pow_2_scheduler.py multiplexed ranking); affinity
information rides the existing queue-length probes instead of a
controller round-trip.

TPU note: "model" here is typically a jitted apply fn + weights pytree;
multiplexing lets one replica (one chip reservation) serve many LoRA
variants or small models, evicting least-recently-used weights from HBM.
"""
import asyncio
import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Any, Callable, List, Optional

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the current request (reference:
    serve.get_multiplexed_model_id) — set from the handle option
    `multiplexed_model_id` or the `serve_multiplexed_model_id` HTTP
    header; empty string outside a multiplexed request."""
    return _model_id_ctx.get()


def _set_request_model_id(model_id: str):
    """Replica-internal: bind the request's model id into the context."""
    return _model_id_ctx.set(model_id)


async def _cleanup_evicted(evicted: Any):
    """Run an evicted model's `__del__` eagerly (resources — HBM — must
    free NOW, not at GC time; async `__del__`s could never be awaited by
    GC at all), then neuter the class-level `__del__` so garbage
    collection doesn't run the cleanup a second time."""
    del_fn = getattr(evicted, "__del__", None)
    if not callable(del_fn):
        return
    try:
        out = del_fn()
        if inspect.isawaitable(out):
            await out
    except Exception:
        pass
    try:
        cls = type(evicted)
        evicted.__class__ = type(
            "_Evicted" + cls.__name__, (cls,),
            {"__del__": lambda self: None})
    except TypeError:
        pass  # non-heap/layout-locked types: accept a double __del__


class _ModelMultiplexWrapper:
    """Per-replica LRU model cache (reference:
    serve/multiplexed.py _ModelMultiplexWrapper)."""

    def __init__(self, loader: Callable, owner: Any,
                 max_num_models_per_replica: int):
        self._loader = loader
        self._owner = owner
        self._max = int(max_num_models_per_replica)
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = asyncio.Lock()

    @property
    def model_ids(self) -> List[str]:
        return list(self._models.keys())

    async def load_model(self, model_id: str) -> Any:
        if not isinstance(model_id, str) or not model_id:
            raise ValueError(
                "multiplexed model_id must be a non-empty string, got "
                f"{model_id!r}")
        async with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            while len(self._models) >= self._max > 0:
                evicted_id, evicted = self._models.popitem(last=False)
                await _cleanup_evicted(evicted)
            args = (self._owner, model_id) if self._owner is not None \
                else (model_id,)
            model = self._loader(*args)
            if inspect.isawaitable(model):
                model = await model
            self._models[model_id] = model
            return model


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a deployment's model-loader method (reference:
    serve/api.py multiplexed). Usage:

        @serve.deployment
        class M:
            @serve.multiplexed(max_num_models_per_replica=4)
            async def get_model(self, model_id: str):
                return load_weights(model_id)

            async def __call__(self, req):
                model = await self.get_model(
                    serve.get_multiplexed_model_id())
                ...

    The wrapped method returns the cached model, loading (and LRU
    evicting) as needed; the loader runs at most once per cached id.
    """
    if max_num_models_per_replica <= 0:
        raise ValueError("max_num_models_per_replica must be positive")

    def decorator(fn: Callable):
        @functools.wraps(fn)
        async def wrapped(self, model_id: Optional[str] = None):
            # Wrappers are keyed by loader name so multiple @multiplexed
            # methods on one class (model + tokenizer) keep separate
            # caches instead of silently returning each other's objects.
            wrappers = getattr(self, "__serve_mux_wrappers__", None)
            if wrappers is None:
                wrappers = {}
                setattr(self, "__serve_mux_wrappers__", wrappers)
            wrapper = wrappers.get(fn.__name__)
            if wrapper is None:
                wrapper = wrappers[fn.__name__] = _ModelMultiplexWrapper(
                    fn, self, max_num_models_per_replica)
            if model_id is None:
                model_id = get_multiplexed_model_id()
            return await wrapper.load_model(model_id)

        return wrapped

    if func is not None:
        return decorator(func)
    return decorator


def loaded_model_ids(user_callable: Any) -> List[str]:
    """Model ids currently cached on a replica's user object, across
    every multiplexed method (probed by the router for model-aware
    routing)."""
    wrappers = getattr(user_callable, "__serve_mux_wrappers__", None)
    if not wrappers:
        return []
    out: List[str] = []
    for w in wrappers.values():
        for mid in w.model_ids:
            if mid not in out:
                out.append(mid)
    return out
