"""ASGI ingress for Serve deployments.

Reference parity: ``@serve.ingress(app)`` (python/ray/serve/api.py:170)
— wrap a FastAPI/Starlette/any-ASGI application as a deployment's HTTP
surface. The proxy forwards the raw request (method, path, query,
headers, body); the replica drives one ASGI request/response cycle
through the app and ships back status + headers + body, which the proxy
replays verbatim. Works with ANY ASGI3 callable — FastAPI is just the
common case (not bundled in this environment; the tests use a plain
ASGI app).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

__all__ = ["ingress"]


async def _run_asgi_once(app, req: Dict[str, Any]) -> Dict[str, Any]:
    """Drive one http request through an ASGI3 app; returns the proxy
    replay envelope."""
    # Prefer the undecoded path (proxy's raw_path): percent-encoded
    # metacharacters must reach the app's own query parser intact. Per
    # the ASGI spec, scope["path"] is DECODED while query_string and
    # raw_path stay encoded.
    from urllib.parse import unquote
    path_qs = req.get("raw_path") or req.get("path", "/")
    raw_path, _, query = path_qs.partition("?")
    path = unquote(raw_path)
    prefix = req.get("route_prefix") or ""
    if prefix == "/":
        prefix = ""  # root mount: no prefix to strip (ASGI root_path "")
    if prefix and path.startswith(prefix):
        sub_path = path[len(prefix):] or "/"
    else:
        sub_path = path
    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": req.get("method", "GET"),
        "scheme": "http",
        # root_path carries the deployment's route prefix so apps with
        # absolute routes mount correctly (reference: serve mounts the
        # FastAPI app at the route prefix).
        "root_path": prefix,
        "path": sub_path,
        "raw_path": raw_path.encode(),
        "query_string": query.encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in (req.get("headers") or [])],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 80),
    }
    body = req.get("raw_body")
    if body is None:
        body = b""
    elif isinstance(body, str):
        body = body.encode()

    sent = {"body": body, "done": False}

    async def receive():
        if sent["done"]:
            return {"type": "http.disconnect"}
        sent["done"] = True
        return {"type": "http.request", "body": sent["body"],
                "more_body": False}

    out: Dict[str, Any] = {"status": 200, "headers": [], "chunks": []}

    async def send(message):
        if message["type"] == "http.response.start":
            out["status"] = int(message["status"])
            out["headers"] = [
                (k.decode() if isinstance(k, (bytes, bytearray)) else k,
                 v.decode() if isinstance(v, (bytes, bytearray)) else v)
                for k, v in message.get("headers", [])]
        elif message["type"] == "http.response.body":
            chunk = message.get("body", b"")
            if chunk:
                out["chunks"].append(bytes(chunk))

    await app(scope, receive, send)
    return {"__asgi__": True, "status": out["status"],
            "headers": out["headers"], "body": b"".join(out["chunks"])}


import os as _os

# Each live websocket's ws_stream generator occupies one replica
# executor thread for the connection's lifetime; the replica's pool has
# max(2*max_ongoing_requests, 16) threads, so the connection count must
# stay safely below it or queued work (including the disconnects that
# would free the threads) deadlocks behind the blocked generators.
_WS_PER_REPLICA = int(_os.environ.get("RAY_TPU_SERVE_WS_PER_REPLICA",
                                      "8"))


class _WsConn:
    """One live websocket's replica-side state: inbound events ride an
    asyncio queue consumed by the app's receive() on the actor loop;
    outbound events ride a THREAD-SAFE queue drained by the sync
    ws_stream generator on the replica's streaming thread. Inbound
    frames carry proxy-assigned sequence numbers and are released to
    the app in order (ws_push tasks run on a multi-threaded executor,
    so arrival order alone is not delivery order)."""

    def __init__(self):
        import asyncio
        import queue
        self.in_q: "asyncio.Queue" = asyncio.Queue()
        self.out_q: "queue.Queue" = queue.Queue()
        self.task = None
        self.next_seq = 0
        self.pending: dict = {}  # seq -> message (actor-loop only)

    async def deliver(self, seq: int, msg: dict) -> None:
        """Release messages to the app in sequence order. Runs only on
        the actor loop, so the reorder state needs no lock."""
        self.pending[seq] = msg
        while self.next_seq in self.pending:
            await self.in_q.put(self.pending.pop(self.next_seq))
            self.next_seq += 1


async def _run_asgi_ws(app, conn: _WsConn, req: Dict[str, Any]) -> None:
    """Drive one websocket connection cycle through an ASGI3 app."""
    from urllib.parse import unquote

    path_qs = req.get("raw_path") or req.get("path", "/")
    raw_path, _, query = path_qs.partition("?")
    path = unquote(raw_path)
    prefix = req.get("route_prefix") or ""
    if prefix == "/":
        prefix = ""
    sub_path = path[len(prefix):] or "/" if (
        prefix and path.startswith(prefix)) else path
    scope = {
        "type": "websocket",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "scheme": "ws",
        "root_path": prefix,
        "path": sub_path,
        "raw_path": raw_path.encode(),
        "query_string": query.encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in (req.get("headers") or [])],
        "subprotocols": [],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 80),
    }
    started = {"connect": False}

    async def receive():
        if not started["connect"]:
            started["connect"] = True
            return {"type": "websocket.connect"}
        return await conn.in_q.get()

    async def send(message):
        t = message["type"]
        if t == "websocket.accept":
            conn.out_q.put(("accept",
                            message.get("subprotocol") or ""))
        elif t == "websocket.send":
            if message.get("text") is not None:
                conn.out_q.put(("text", message["text"]))
            else:
                conn.out_q.put(("bytes", message.get("bytes", b"")))
        elif t == "websocket.close":
            conn.out_q.put(("close", int(message.get("code", 1000))))

    try:
        await app(scope, receive, send)
    except BaseException:
        # App crashed mid-connection: tell the client it was an
        # ERROR close (1011), not a clean end, and keep the traceback
        # observable instead of dying silently in a dropped task.
        import logging
        logging.getLogger(__name__).exception(
            "ASGI websocket app raised")
        conn.out_q.put(("close", 1011))
    finally:
        # End the outbound stream so the proxy's pump terminates and
        # closes the client socket.
        conn.out_q.put(("__end__", None))


def ingress(app) -> Callable[[type], type]:
    """Class decorator: route the deployment's HTTP traffic through an
    ASGI app (reference: serve/api.py:170 ``@serve.ingress``). Methods
    on the class remain callable through deployment handles; HTTP
    requests run one ASGI cycle and replay the app's real status code,
    headers, and body through the proxy.

    Usage::

        app = FastAPI()          # or any ASGI3 callable

        @serve.deployment
        @serve.ingress(app)
        class Api:
            @app.get("/hello")
            def hello(self):
                return {"msg": "hi"}
    """

    def decorator(cls: type) -> type:
        if not callable(app):
            raise TypeError(
                f"serve.ingress expects an ASGI app, got {type(app)}")

        class _ASGIIngress(cls):  # type: ignore[misc,valid-type]
            __serve_asgi_app__ = app

            async def __call__(self, request):
                if not isinstance(request, dict):
                    request = {"path": "/", "method": "GET",
                               "raw_body": None, "headers": []}
                return await _run_asgi_once(
                    type(self).__serve_asgi_app__, request)

            # -- websocket pass-through (reference: the ASGI proxy
            # carrying websocket scopes, serve/_private/proxy.py:418).
            # The proxy pins one replica per connection and drives
            # these: ws_open starts the app cycle on the actor loop,
            # ws_push feeds client frames, ws_stream streams outbound
            # events back, ws_close injects the disconnect. --
            def _ws_conns(self) -> Dict[str, _WsConn]:
                d = self.__dict__.get("__serve_ws_conns__")
                if d is None:
                    d = {}
                    self.__dict__["__serve_ws_conns__"] = d
                return d

            async def ws_open(self, conn_id: str, req: dict) -> bool:
                import asyncio
                conns = self._ws_conns()
                if len(conns) >= _WS_PER_REPLICA:
                    # Capacity, not deadlock: every live socket holds
                    # one executor thread (see _WS_PER_REPLICA); the
                    # proxy closes the upgrade when we refuse.
                    return False
                conn = _WsConn()
                conns[conn_id] = conn
                conn.task = asyncio.get_running_loop().create_task(
                    _run_asgi_ws(type(self).__serve_asgi_app__, conn,
                                 req))
                return True

            async def ws_push(self, conn_id: str, seq: int, kind: str,
                              data) -> bool:
                conn = self._ws_conns().get(conn_id)
                if conn is None:
                    return False
                msg = {"type": "websocket.receive"}
                if kind == "text":
                    msg["text"] = data
                else:
                    msg["bytes"] = data
                await conn.deliver(seq, msg)
                return True

            async def ws_close(self, conn_id: str, seq: int,
                               code: int = 1000) -> bool:
                import asyncio
                conn = self._ws_conns().pop(conn_id, None)
                if conn is None:
                    return False
                # The disconnect takes its place IN SEQUENCE after the
                # last client frame — it must not overtake one.
                await conn.deliver(seq, {"type": "websocket.disconnect",
                                         "code": code})
                if conn.task is not None:
                    # Grace for the app to unwind on the disconnect,
                    # then cancel a straggler so the task can't leak.
                    task = conn.task

                    async def _reap():
                        await asyncio.sleep(5.0)
                        if not task.done():
                            task.cancel()
                    asyncio.get_running_loop().create_task(_reap())
                return True

            def ws_stream(self, conn_id: str):
                conn = self._ws_conns().get(conn_id)
                if conn is None:
                    return
                while True:
                    kind, data = conn.out_q.get()
                    if kind == "__end__":
                        return
                    yield (kind, data)

        _ASGIIngress.__name__ = cls.__name__
        _ASGIIngress.__qualname__ = getattr(cls, "__qualname__",
                                            cls.__name__)
        _ASGIIngress.__module__ = cls.__module__
        return _ASGIIngress

    return decorator
