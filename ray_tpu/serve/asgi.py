"""ASGI ingress for Serve deployments.

Reference parity: ``@serve.ingress(app)`` (python/ray/serve/api.py:170)
— wrap a FastAPI/Starlette/any-ASGI application as a deployment's HTTP
surface. The proxy forwards the raw request (method, path, query,
headers, body); the replica drives one ASGI request/response cycle
through the app and ships back status + headers + body, which the proxy
replays verbatim. Works with ANY ASGI3 callable — FastAPI is just the
common case (not bundled in this environment; the tests use a plain
ASGI app).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

__all__ = ["ingress"]


async def _run_asgi_once(app, req: Dict[str, Any]) -> Dict[str, Any]:
    """Drive one http request through an ASGI3 app; returns the proxy
    replay envelope."""
    # Prefer the undecoded path (proxy's raw_path): percent-encoded
    # metacharacters must reach the app's own query parser intact. Per
    # the ASGI spec, scope["path"] is DECODED while query_string and
    # raw_path stay encoded.
    from urllib.parse import unquote
    path_qs = req.get("raw_path") or req.get("path", "/")
    raw_path, _, query = path_qs.partition("?")
    path = unquote(raw_path)
    prefix = req.get("route_prefix") or ""
    if prefix == "/":
        prefix = ""  # root mount: no prefix to strip (ASGI root_path "")
    if prefix and path.startswith(prefix):
        sub_path = path[len(prefix):] or "/"
    else:
        sub_path = path
    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": req.get("method", "GET"),
        "scheme": "http",
        # root_path carries the deployment's route prefix so apps with
        # absolute routes mount correctly (reference: serve mounts the
        # FastAPI app at the route prefix).
        "root_path": prefix,
        "path": sub_path,
        "raw_path": raw_path.encode(),
        "query_string": query.encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in (req.get("headers") or [])],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 80),
    }
    body = req.get("raw_body")
    if body is None:
        body = b""
    elif isinstance(body, str):
        body = body.encode()

    sent = {"body": body, "done": False}

    async def receive():
        if sent["done"]:
            return {"type": "http.disconnect"}
        sent["done"] = True
        return {"type": "http.request", "body": sent["body"],
                "more_body": False}

    out: Dict[str, Any] = {"status": 200, "headers": [], "chunks": []}

    async def send(message):
        if message["type"] == "http.response.start":
            out["status"] = int(message["status"])
            out["headers"] = [
                (k.decode() if isinstance(k, (bytes, bytearray)) else k,
                 v.decode() if isinstance(v, (bytes, bytearray)) else v)
                for k, v in message.get("headers", [])]
        elif message["type"] == "http.response.body":
            chunk = message.get("body", b"")
            if chunk:
                out["chunks"].append(bytes(chunk))

    await app(scope, receive, send)
    return {"__asgi__": True, "status": out["status"],
            "headers": out["headers"], "body": b"".join(out["chunks"])}


def ingress(app) -> Callable[[type], type]:
    """Class decorator: route the deployment's HTTP traffic through an
    ASGI app (reference: serve/api.py:170 ``@serve.ingress``). Methods
    on the class remain callable through deployment handles; HTTP
    requests run one ASGI cycle and replay the app's real status code,
    headers, and body through the proxy.

    Usage::

        app = FastAPI()          # or any ASGI3 callable

        @serve.deployment
        @serve.ingress(app)
        class Api:
            @app.get("/hello")
            def hello(self):
                return {"msg": "hi"}
    """

    def decorator(cls: type) -> type:
        if not callable(app):
            raise TypeError(
                f"serve.ingress expects an ASGI app, got {type(app)}")

        class _ASGIIngress(cls):  # type: ignore[misc,valid-type]
            __serve_asgi_app__ = app

            async def __call__(self, request):
                if not isinstance(request, dict):
                    request = {"path": "/", "method": "GET",
                               "raw_body": None, "headers": []}
                return await _run_asgi_once(
                    type(self).__serve_asgi_app__, request)

        _ASGIIngress.__name__ = cls.__name__
        _ASGIIngress.__qualname__ = getattr(cls, "__qualname__",
                                            cls.__name__)
        _ASGIIngress.__module__ = cls.__module__
        return _ASGIIngress

    return decorator
