"""Caller-side serve data plane: the proxy's direct channels to
replica workers.

The serve request path used to route proxy -> head -> replica as a
head-brokered handle call per request. Here the proxy process (the
driver, or a worker hosting a ProxyReplica) holds ONE brokered channel
per replica worker (same-node UNIX, cross-node TCP — the PR 5
`_private/direct.py` listener accepts any number of peers) and ships
SERVE_REQ/SERVE_RESP frames on it: steady-state requests are pure
channel hops and the head hears NOTHING per request. Bodies above
``serve_direct_body_threshold`` move zero-copy through the shared
same-node arena (direct.serve_encode_body / serve_decode_body).

Failure semantics: channel EOF fails every in-flight request with a
typed ReplicaUnavailableError — the proxy surfaces 503, never a hang
(replica SIGKILL mid-request is the test). Establishment is fully
non-blocking: ``channel_for()`` returns None until a background thread
has brokered + dialed, and early requests ride the classic head path
meanwhile (exactly the transient-establish behavior of the actor-call
plane).

Flag-off discipline (``serve_direct_enabled=false``): the dispatch
helper returns before calling into this module, and the counter below
proves it — the same guarded-counter pattern as ``direct.direct_ops``
(scripts/ci_fast.sh runs the guard standalone).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Set

from ray_tpu._private import protocol as P
from ray_tpu._private import serialization
from ray_tpu._private import state as _state
from ray_tpu._private import telemetry
from ray_tpu._private import wiretap
from ray_tpu._private.direct import (DirectPlane, serve_decode_body,
                                     serve_encode_body)

logger = logging.getLogger(__name__)

# Counter of serve-direct operations in THIS process — the perf_smoke
# guard's counter-based proxy for "the disabled path did no
# serve-direct work".
_ops = 0


def serve_direct_ops() -> int:
    """Serve-direct operations performed so far (perf_smoke guard)."""
    return _ops


def _bump() -> None:
    global _ops
    _ops += 1


class ReplicaUnavailableError(Exception):
    """The replica's channel died with the request in flight (worker
    SIGKILL, node loss): the proxy surfaces 503, never a hang."""


class ReplicaQueueFullError(Exception):
    """Every replica's proxy-tracked queue is at
    ``serve_max_queue_per_replica``: shed with 503 at the edge."""


def _env():
    """(store, node_id_hex) of THIS process, or None before init."""
    w = _state._worker
    if w is not None:
        return w.store, w.config.node_id_hex
    node = _state.get_node()
    if node is not None:
        return node.store, node.node_id.hex()
    return None


def _broker(actor_id) -> dict:
    """One CHANNEL_REQ round trip from whichever process we are in: the
    driver asks its in-process broker, a worker asks over its head pipe
    (same reply shape either way)."""
    w = _state._worker
    if w is not None:
        rep = w.request(P.CHANNEL_REQ, {"actor_id": actor_id})
        return rep if isinstance(rep, dict) else {
            "ok": False, "reason": repr(rep)}
    node = _state.get_node()
    if node is not None:
        return node.broker_serve_channel(actor_id)
    return {"ok": False, "reason": "runtime not initialized"}


class _ServeChannel:
    """One live channel to one replica worker: a coalescing writer, a
    recv thread completing rid-keyed futures, and EOF fan-out of every
    in-flight request to a typed error."""

    __slots__ = ("client", "actor_ab", "conn", "writer", "store",
                 "same_node", "alive", "_lock", "_rid", "_inflight")

    def __init__(self, client: "ServeDirectClient", actor_ab: bytes,
                 conn, store, same_node: bool):
        self.client = client
        self.actor_ab = actor_ab
        self.conn = conn
        self.store = store
        self.same_node = same_node
        self.alive = True
        self._lock = threading.Lock()
        self._rid = 0
        self._inflight: Dict[int, Future] = {}
        from ray_tpu._private.netcomm import ConnectionWriter
        self.writer = ConnectionWriter(conn, name="serve-direct-w")
        threading.Thread(target=self._recv_loop, daemon=True,
                         name="serve-direct-recv").start()

    def call(self, method: str, args: tuple, kwargs: dict,
             trace_ctx=None) -> Future:
        """Ship one request; the returned Future resolves to the
        decoded response value or raises the replica's typed error."""
        _bump()
        body = serve_encode_body(self.store, (args, kwargs),
                                 self.same_node)
        fut: Future = Future()
        with self._lock:
            if not self.alive:
                self._reclaim_body(body)
                raise ReplicaUnavailableError(
                    "replica channel is down")
            self._rid += 1
            rid = self._rid
            self._inflight[rid] = fut
        msg = {"r": rid, "m": method, "b": body, "sn": self.same_node}
        if trace_ctx:
            msg["tr"] = trace_ctx
        if wiretap.enabled:
            wiretap.frame("direct", "caller", id(self), "send",
                          P.SERVE_REQ, msg)
        try:
            self.writer.send_message(P.SERVE_REQ, msg)
        except Exception:
            with self._lock:
                self._inflight.pop(rid, None)
            self._reclaim_body(body)
            raise ReplicaUnavailableError(
                "replica channel send failed") from None
        return fut

    def _reclaim_body(self, body) -> None:
        """A request body we arena-staged never reached the replica:
        free the slot ourselves (we are its producer)."""
        if body is not None and body[0] == "o":
            from ray_tpu._private.ids import ObjectID
            try:
                self.store.free(ObjectID(body[1]))
            except Exception:  # lint: broad-except-ok teardown race; the arena dies with the session
                pass

    def _recv_loop(self):
        while True:
            try:
                data = self.conn.recv_bytes()
            except (EOFError, OSError):
                break
            try:
                for msg_type, payload in P.load_messages(data):
                    if wiretap.enabled:
                        wiretap.frame("direct", "caller", id(self),
                                      "recv", msg_type, payload)
                    if msg_type == P.SERVE_RESP:
                        self._on_resp(payload)
                    elif msg_type == P.SERVE_BODY_FREE:
                        self._on_body_free(payload)
                    else:
                        logger.warning(
                            "serve channel dropping unknown message "
                            "type %r (protocol skew?)", msg_type)
            except Exception:
                logger.exception("serve channel handler failed")
        self._down()

    def _on_resp(self, payload: dict) -> None:
        _bump()
        with self._lock:
            fut = self._inflight.pop(payload.get("r"), None)
        if fut is None:
            return  # channel raced down; the EOF fan-out beat us
        blob = payload.get("e")
        if blob is not None:
            try:
                err = serialization.deserialize(blob)
            except Exception as e:  # lint: broad-except-ok undecodable error blob still fails the request typed
                err = e
            fut.set_exception(err)
            return
        try:
            value, free_ob = serve_decode_body(self.store, payload["v"])
            if free_ob is not None:
                # Response body was arena-staged by the replica: ack so
                # it releases the slot (reader pins keep our decoded
                # views safe across the free).
                if wiretap.enabled:
                    wiretap.frame("direct", "caller", id(self), "send",
                                  P.SERVE_BODY_FREE, {"o": free_ob})
                self.writer.send_message(P.SERVE_BODY_FREE,
                                         {"o": free_ob})
            fut.set_result(value)
        except BaseException as e:  # noqa: BLE001 — ships to the waiter
            fut.set_exception(e)

    def _on_body_free(self, payload: dict) -> None:
        """The replica finished decoding a request body we staged."""
        _bump()
        from ray_tpu._private.ids import ObjectID
        try:
            self.store.free(ObjectID(payload["o"]))
        except Exception:  # lint: broad-except-ok double-free after teardown is harmless
            pass

    def _down(self) -> None:
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            pending = list(self._inflight.values())
            self._inflight.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(ReplicaUnavailableError(
                    "replica channel closed with the request in "
                    "flight (replica died or is being torn down)"))
        try:
            self.writer.close(flush_timeout=0.0)
        except Exception:  # lint: broad-except-ok writer already dead with the channel
            pass
        try:
            self.conn.close()
        except OSError:
            pass
        self.client._forget(self.actor_ab, self)

    def close(self) -> None:
        try:
            self.conn.close()  # recv loop EOF runs the _down fan-out
        except OSError:
            pass


class ServeDirectClient:
    """Per-process registry of serve channels, keyed by replica actor
    id. ``channel_for()`` NEVER blocks: establishment (broker round
    trip + dial) runs on a background thread and requests fall back to
    the classic path until the channel is live. Failed establishment
    backs off ``direct_redial_backoff_s`` before retrying, mirroring
    the actor-call plane's redial discipline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._chans: Dict[bytes, _ServeChannel] = {}
        self._pending: Set[bytes] = set()
        self._failed_at: Dict[bytes, float] = {}

    def channel_for(self, replica) -> Optional[_ServeChannel]:
        actor_id = getattr(replica, "_actor_id", None)
        if actor_id is None:
            return None
        ab = actor_id.binary()
        with self._lock:
            ch = self._chans.get(ab)
            if ch is not None and ch.alive:
                return ch
            if ch is not None:
                self._chans.pop(ab, None)
            if ab in self._pending:
                return None
            from ray_tpu._private.config import ray_config
            ts = self._failed_at.get(ab)
            if ts is not None and time.monotonic() - ts < float(
                    ray_config.direct_redial_backoff_s):
                return None
            self._pending.add(ab)
        _bump()
        threading.Thread(target=self._establish, args=(actor_id,),
                         daemon=True, name="serve-direct-dial").start()
        return None

    def _establish(self, actor_id) -> None:
        ab = actor_id.binary()
        try:
            env = _env()
            if env is None:
                raise RuntimeError("runtime not initialized")
            store, my_node = env
            rep = _broker(actor_id)
            if not rep.get("ok"):
                raise RuntimeError(rep.get("reason") or "broker refused")
            from ray_tpu._private.config import ray_config
            key = bytes.fromhex(rep["key"])
            budget = float(ray_config.direct_channel_timeout_s)
            if rep.get("unix") and (not rep.get("callee_node")
                                    or rep["callee_node"] == my_node
                                    or my_node is None):
                conn = DirectPlane._dial(rep["unix"], "AF_UNIX", key,
                                         budget)
                same_node = True
            elif rep.get("tcp"):
                host, port = rep["tcp"]
                conn = DirectPlane._dial((host, int(port)), "AF_INET",
                                         key, budget)
                from ray_tpu._private.netcomm import tune_control_socket
                tune_control_socket(conn.fileno())
                same_node = rep.get("callee_node") == my_node
            else:
                raise RuntimeError(
                    "broker reply carries no dialable address")
            ch = _ServeChannel(self, ab, conn, store, same_node)
        except Exception as e:  # lint: broad-except-ok any establish failure degrades to the head path
            logger.debug("serve direct channel to %s unavailable: %r "
                         "(head path)", actor_id.hex()[:8], e)
            if telemetry.enabled:
                telemetry.record_direct_fallback("serve_connect")
            with self._lock:
                self._failed_at[ab] = time.monotonic()
                self._pending.discard(ab)
            return
        with self._lock:
            self._pending.discard(ab)
            self._failed_at.pop(ab, None)
            self._chans[ab] = ch

    def _forget(self, ab: bytes, ch: _ServeChannel) -> None:
        with self._lock:
            if self._chans.get(ab) is ch:
                del self._chans[ab]

    def close(self) -> None:
        with self._lock:
            chans = list(self._chans.values())
            self._chans.clear()
            self._pending.clear()
            self._failed_at.clear()
        for ch in chans:
            ch.close()


_client_lock = threading.Lock()
_client: Optional[ServeDirectClient] = None
_client_owner = None


def get_client() -> Optional[ServeDirectClient]:
    """The process-wide client, rebuilt when the runtime identity
    changes — tests init/shutdown clusters repeatedly in one process,
    and channels to a dead cluster's workers must not survive into the
    next one."""
    global _client, _client_owner
    cur = _state.current_or_none()
    if cur is None:
        return None
    old = None
    with _client_lock:
        if _client is None or _client_owner is not cur:
            old, _client = _client, ServeDirectClient()
            _client_owner = cur
        client = _client
    if old is not None:
        old.close()
    return client


def reset_client() -> None:
    """Close every channel (serve.shutdown / runtime teardown)."""
    global _client, _client_owner
    with _client_lock:
        old, _client, _client_owner = _client, None, None
    if old is not None:
        old.close()
