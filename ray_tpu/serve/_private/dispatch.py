"""Shared serve dispatch: HTTP and gRPC ingress both route unary
requests through ``try_direct`` so the direct-plane fast path, the
load-aware routing, and the shed-with-503 admission control cannot
fork per protocol.

Order of attempts per request (HTTPProxy._handle_inner / GRPCProxy):

  1. ``try_direct`` — least-loaded replica claim + SERVE_REQ on the
     brokered channel (this module); None means "not available yet"
     (flag off, router unbuilt, channel still establishing) and the
     caller falls back to
  2. the classic DeploymentHandle path (head-brokered handle call).

``ReplicaQueueFullError`` propagates: admission control applies to the
request itself, not to the direct plane — a full queue must NOT
quietly retry through the head path (that queue is the wedged pool the
backpressure exists to protect).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ray_tpu._private import telemetry
from ray_tpu.util import tracing


class DirectResponse:
    """Future-like result of a direct-plane dispatch: awaitable on the
    proxy's event loop AND blocking for the gRPC thread pool — the
    same dual surface DeploymentResponse offers both callers."""

    __slots__ = ("_fut",)

    def __init__(self, fut):
        self._fut = fut

    def result(self, timeout_s: Optional[float] = None):
        return self._fut.result(timeout=timeout_s)

    def __await__(self):
        return asyncio.wrap_future(self._fut).__await__()


def try_direct(handle, args: tuple, kwargs: dict
               ) -> Optional[DirectResponse]:
    """One direct-plane dispatch attempt for a unary request. Returns a
    DirectResponse, or None to take the classic handle path; raises
    ReplicaQueueFullError when admission control sheds.

    Flag-off (``serve_direct_enabled=false``) returns None BEFORE
    touching any serve-direct state — the zero-work discipline the
    counter guard in tests/test_serve_direct.py proves."""
    from ray_tpu._private.config import ray_config
    if not bool(ray_config.serve_direct_enabled):
        return None
    if handle._stream:
        return None
    router = handle._router
    if router is None:
        return None
    claim = router.try_claim_direct(handle._model_id)  # may shed
    if claim is None:
        return None
    idx, replica, release = claim
    from . import direct_client as _dc
    client = _dc.get_client()
    chan = client.channel_for(replica) if client is not None else None
    if chan is None:
        release()
        return None
    trace_ctx = tracing.current_context() if tracing.is_enabled() \
        else None
    try:
        fut = chan.call(
            "handle_request",
            (handle._method, args, kwargs, handle._model_id), {},
            trace_ctx)
    except _dc.ReplicaUnavailableError:
        release()
        return None  # channel died under us: this request heads back
    fut.add_done_callback(lambda _f: release())
    if telemetry.enabled:
        telemetry.serve_direct_request(handle.deployment_name)
        telemetry.serve_queue_depth(handle.deployment_name,
                                    router.total_inflight())
    return DirectResponse(fut)
