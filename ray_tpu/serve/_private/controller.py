"""ServeController: the control-plane actor.

Reference: python/ray/serve/_private/controller.py:84 (ServeController) +
deployment_state.py / application_state.py (reconciliation) +
autoscaling_state.py (replica autoscaling). One async actor owns desired
state (applications -> deployments -> target replica counts), runs a
reconcile loop that starts/stops/heals replica actors, and broadcasts
replica membership + routes to routers/proxies over long-poll
(long_poll.py). The request path never touches this actor.
"""
import asyncio
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from .long_poll import LongPollHost
from .replica import start_replica

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _DeploymentState:
    def __init__(self, info: Dict[str, Any]):
        self.info = info                  # config fields, cls_blob, args
        self.replicas: List = []          # live actor handles
        self.replica_seq = 0              # monotonic replica name suffix
        self.target = info["initial_replicas"]
        self.last_upscale_ok_t = 0.0      # autoscaling decision debounce
        self.last_downscale_ok_t = 0.0


class ServeController:
    """Async controller actor (reference: controller.py:84)."""

    def __init__(self):
        self._apps: Dict[str, List[str]] = {}           # app -> deployments
        self._deployments: Dict[str, _DeploymentState] = {}
        self._routes: Dict[str, tuple] = {}             # prefix -> (app, dep)
        self._long_poll = LongPollHost()
        self._shutdown = False
        # Per-node proxy reconciliation (reference: proxy_state.py
        # ProxyStateManager): node_hex -> (actor_handle, (host, port)).
        self._proxies: Dict[str, tuple] = {}
        self._proxy_config: Optional[Dict[str, Any]] = None
        self._proxy_errors: Dict[str, str] = {}
        # The reconcile task is started lazily from the first async method:
        # __init__ runs on the worker's main thread, while async actor
        # methods run on the dedicated actor event loop (worker_proc.py
        # _ensure_actor_loop) — the task must live on that loop.
        self._loop_task = None

    def _ensure_loop_task(self):
        if self._loop_task is None or self._loop_task.done():
            if not self._shutdown:
                self._loop_task = asyncio.get_event_loop().create_task(
                    self._reconcile_loop())

    # -- API used by serve.run / handles / proxy ---------------------------
    async def deploy_application(self, app_name: str,
                                 deployments: List[Dict[str, Any]],
                                 route_prefix: Optional[str],
                                 ingress: str) -> bool:
        """Reference: application_state.py apply_app_config."""
        self._ensure_loop_task()
        old = set(self._apps.get(app_name, []))
        new_names = []
        for dep in deployments:
            name = dep["name"]
            new_names.append(name)
            existing = self._deployments.get(name)
            if existing is not None and self._same_target(existing.info, dep):
                # In-place update: user_config / replica count only.
                existing.info.update(dep)
                if dep.get("autoscaling_config") is None:
                    existing.target = dep["initial_replicas"]
                if dep.get("user_config") is not None:
                    for r in existing.replicas:
                        r.reconfigure.remote(dep["user_config"])
                continue
            if existing is not None:
                await self._stop_deployment(name)
            self._deployments[name] = _DeploymentState(dep)
        for stale in old - set(new_names):
            await self._stop_deployment(stale)
            self._deployments.pop(stale, None)
        self._apps[app_name] = new_names
        if route_prefix is not None:
            self._routes[route_prefix] = (app_name, ingress)
            self._long_poll.notify_changed("routes", dict(self._routes))
        await self._reconcile_once()
        return True

    @staticmethod
    def _same_target(old_info: Dict, new_info: Dict) -> bool:
        return (old_info["cls_blob"] == new_info["cls_blob"]
                and old_info["init_args"] == new_info["init_args"]
                and old_info["init_kwargs"] == new_info["init_kwargs"]
                and old_info["actor_options"] == new_info["actor_options"])

    async def delete_application(self, app_name: str) -> bool:
        self._ensure_loop_task()
        for name in self._apps.pop(app_name, []):
            await self._stop_deployment(name)
            self._deployments.pop(name, None)
        self._routes = {p: v for p, v in self._routes.items()
                        if v[0] != app_name}
        self._long_poll.notify_changed("routes", dict(self._routes))
        return True

    async def graceful_shutdown(self) -> bool:
        self._shutdown = True
        for name in list(self._deployments):
            await self._stop_deployment(name)
        self._deployments.clear()
        self._apps.clear()
        for node_hex, (handle, _addr) in list(self._proxies.items()):
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        self._proxies.clear()
        return True

    async def listen_for_change(self, snapshot_ids: Dict[str, int],
                                timeout_s: float = 30.0):
        self._ensure_loop_task()
        return await self._long_poll.listen_for_change(snapshot_ids,
                                                       timeout_s)

    async def get_replica_snapshot(self, deployment: str) -> List:
        self._ensure_loop_task()
        st = self._deployments.get(deployment)
        return list(st.replicas) if st else []

    async def get_route_table(self) -> Dict[str, tuple]:
        self._ensure_loop_task()
        return dict(self._routes)

    async def list_deployments(self) -> Dict[str, Dict[str, Any]]:
        self._ensure_loop_task()
        return {
            name: {"target_replicas": st.target,
                   "live_replicas": len(st.replicas),
                   "app": next((a for a, ds in self._apps.items()
                                if name in ds), None)}
            for name, st in self._deployments.items()
        }

    async def drain_node(self, node_id_hex: str) -> int:
        """Pull every replica living on `node_id_hex` out of routing,
        wait for their in-flight requests to finish, then stop them.

        Order matters for the zero-failed-requests guarantee: routers
        learn the shrunken membership over long-poll *before* any
        replica dies, so no new request is dispatched to a victim, and
        victims are only killed once their queue reports empty.
        Replacement replicas come back via the ordinary reconcile loop
        (the scheduler refuses draining nodes, so they land elsewhere).
        """
        self._ensure_loop_task()
        loop = asyncio.get_event_loop()
        try:
            from ray_tpu.util.state import list_actors
            rows = await loop.run_in_executor(None, list_actors)
        except Exception:  # lint: broad-except-ok state API unreachable -> nothing to map, drain 0
            rows = []
        on_node = {r["actor_id"] for r in rows
                   if r.get("node_id") == node_id_hex}
        victims = []
        for name, st in self._deployments.items():
            keep = [r for r in st.replicas
                    if r._actor_id.hex() not in on_node]
            drop = [r for r in st.replicas
                    if r._actor_id.hex() in on_node]
            if drop:
                st.replicas = keep
                self._long_poll.notify_changed(
                    f"replicas::{name}", list(st.replicas))
                victims.extend(drop)
        drained = 0
        for v in victims:
            # Wait until the replica is idle, then require one more
            # empty reading after a short settle so a request that a
            # router dispatched just before it saw the long-poll update
            # is not raced by the kill.
            try:
                while True:
                    if await v.get_queue_len.remote() == 0:
                        await asyncio.sleep(0.2)
                        if await v.get_queue_len.remote() == 0:
                            break
                    else:
                        await asyncio.sleep(0.05)
            except Exception:  # lint: broad-except-ok replica already dead: nothing in flight
                pass
            try:
                ray_tpu.kill(v)
            except Exception:  # lint: broad-except-ok racing actor death; kill is idempotent
                pass
            drained += 1
        return drained

    # -- reconciliation ----------------------------------------------------
    async def _stop_deployment(self, name: str):
        st = self._deployments.get(name)
        if st is None:
            return
        for r in st.replicas:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        st.replicas = []
        self._long_poll.notify_changed(f"replicas::{name}", [])

    def _start_one(self, name: str, st: _DeploymentState):
        info = st.info
        st.replica_seq += 1
        return start_replica(
            name, st.replica_seq, info["cls_blob"], info["init_args"],
            info["init_kwargs"], info["actor_options"],
            info["max_ongoing_requests"], info.get("user_config"))

    async def _reconcile_once(self):
        for name, st in self._deployments.items():
            changed = False
            while len(st.replicas) < st.target:
                st.replicas.append(self._start_one(name, st))
                changed = True
            while len(st.replicas) > st.target:
                victim = st.replicas.pop()
                try:
                    ray_tpu.kill(victim)
                except Exception:
                    pass
                changed = True
            if changed:
                self._long_poll.notify_changed(
                    f"replicas::{name}", list(st.replicas))

    async def _health_and_autoscale(self):
        now = time.monotonic()
        for name, st in self._deployments.items():
            # Health: replace dead replicas (reference:
            # deployment_state.py check_and_update_replicas).
            alive, dead = [], 0
            for r in st.replicas:
                try:
                    ok = await asyncio.wait_for(
                        r.check_health.remote(),
                        timeout=st.info["health_check_timeout_s"])
                    if ok:
                        alive.append(r)
                    else:
                        dead += 1
                except Exception:
                    dead += 1
            if dead or len(alive) != len(st.replicas):
                st.replicas = alive
                self._long_poll.notify_changed(
                    f"replicas::{name}", list(st.replicas))
            # Autoscale on total ongoing requests (reference:
            # autoscaling_policy.py replica-count policy).
            cfg = st.info.get("autoscaling_config")
            if cfg is None or not st.replicas:
                continue
            try:
                lens = await asyncio.gather(
                    *[r.get_queue_len.remote() for r in st.replicas])
            except Exception:
                continue
            desired = cfg.desired_replicas(float(sum(lens)),
                                           len(st.replicas))
            if desired > st.target:
                if st.last_upscale_ok_t == 0.0:
                    st.last_upscale_ok_t = now
                if now - st.last_upscale_ok_t >= cfg.upscale_delay_s:
                    st.target = desired
                    st.last_upscale_ok_t = 0.0
                st.last_downscale_ok_t = 0.0
            elif desired < st.target:
                if st.last_downscale_ok_t == 0.0:
                    st.last_downscale_ok_t = now
                if now - st.last_downscale_ok_t >= cfg.downscale_delay_s:
                    st.target = desired
                    st.last_downscale_ok_t = 0.0
                st.last_upscale_ok_t = 0.0
            else:
                st.last_upscale_ok_t = st.last_downscale_ok_t = 0.0

    # -- per-node proxies --------------------------------------------------
    async def configure_proxies(self, host: str = "0.0.0.0",
                                port: int = 0) -> bool:
        """Enable per-node ingress: the reconcile loop keeps one
        ProxyReplica actor on every alive non-head node (the driver's
        in-process proxy covers the head). Reference: proxy_state.py
        ProxyStateManager.update()."""
        self._ensure_loop_task()
        self._proxy_config = {"host": host, "port": port}
        await self._reconcile_proxies()
        return True

    async def get_proxy_table(self) -> Dict[str, tuple]:
        """node_hex -> (host, port) for every live node proxy."""
        self._ensure_loop_task()
        return {n: addr for n, (_h, addr) in self._proxies.items()
                if addr is not None}

    async def _reconcile_proxies(self):
        if self._proxy_config is None:
            return
        import traceback

        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        from ray_tpu.util.state import list_nodes
        loop = asyncio.get_event_loop()
        try:
            nodes = await loop.run_in_executor(None, list_nodes)
        except Exception:
            self._proxy_errors["_list_nodes"] = traceback.format_exc()
            return
        rows = [n for n in nodes
                if n.get("alive", True) and not n.get("is_head")
                and not n.get("draining")]
        alive = {n["node_id"] for n in rows}
        # The head records each daemon's reachable peer IP at
        # registration; a proxy bound to 0.0.0.0 must be advertised at
        # THAT address, not its bind address.
        node_host = {n["node_id"]: n.get("host") for n in rows}
        # Drop proxies on dead nodes; health-check the rest.
        for node_hex in list(self._proxies):
            handle, _addr = self._proxies[node_hex]
            if node_hex not in alive:
                self._proxies.pop(node_hex, None)
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
                continue
        # Health: a proxy whose server thread died serves
        # connection-refused; replace it (reference: proxy_state.py
        # proxy health states).
        for node_hex, (handle, _addr) in list(self._proxies.items()):
            try:
                ok = await asyncio.wait_for(handle.check_health.remote(),
                                            timeout=15)
            except Exception:
                ok = False
            if not ok:
                self._proxies.pop(node_hex, None)
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
        for node_hex in alive:
            if node_hex in self._proxies:
                continue
            from .proxy import ProxyReplica
            name = f"SERVE_PROXY::{node_hex[:12]}"
            handle = None
            try:
                # Adopt a live orphan first (e.g. a prior reconcile that
                # timed out after the actor booted) — the name is
                # unique, so re-creating would fail forever.
                try:
                    handle = ray_tpu.get_actor(name)
                except Exception:
                    handle = ray_tpu.remote(ProxyReplica).options(
                        name=name,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=node_hex, soft=False),
                    ).remote(self._proxy_config["host"],
                             self._proxy_config["port"])
                addr_ref = handle.address.remote()
                _node, h, p = await asyncio.wait_for(addr_ref, timeout=60)
                if h in ("0.0.0.0", "::") and node_host.get(node_hex):
                    h = node_host[node_hex]
                self._proxies[node_hex] = (handle, (h, p))
                self._proxy_errors.pop(node_hex, None)
            except Exception:
                # Node racing away / worker boot failure: kill the
                # half-created actor (a live orphan would hold the name
                # and wedge every future attempt), keep the last error
                # observable, retry next tick.
                if handle is not None:
                    try:
                        ray_tpu.kill(handle)
                    except Exception:
                        pass
                self._proxy_errors[node_hex] = traceback.format_exc()
                continue

    async def proxy_errors(self) -> Dict[str, str]:
        return dict(self._proxy_errors)

    async def _reconcile_loop(self):
        tick = 0
        while not self._shutdown:
            try:
                await self._reconcile_once()
                if tick % 4 == 1:
                    await self._health_and_autoscale()
                if tick % 8 == 2:
                    await self._reconcile_proxies()
            except Exception:
                pass
            tick += 1
            await asyncio.sleep(0.5)

    async def ping(self) -> bool:
        self._ensure_loop_task()
        return True


def get_controller():
    """Get-or-create the named controller actor (reference:
    serve/_private/api.py _get_global_client)."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    handle = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, max_concurrency=1000).remote()
    ray_tpu.get(handle.ping.remote())
    return handle
