"""Replica actor: hosts one copy of a deployment's user callable.

Reference: python/ray/serve/_private/replica.py — the replica actor
receives requests pushed by routers, tracks ongoing-request count (the
router's power-of-two signal), runs health checks and reconfigure.

TPU note: a replica is where a `jax.jit` model lives; the actor's
`ray_actor_options` reserve TPU chips so the scheduler gives each replica
exclusive chips, and requests run through serve.batch batching so XLA
compiles a handful of bucket shapes once.
"""
import asyncio
import inspect
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu._private import telemetry


class StreamingResponseRequired(Exception):
    """The handler returned a generator on the unary call path; the
    caller must retry via handle_request_streaming."""


class VerdictMismatch(Exception):
    """The proxy trimmed the request per its learned ASGI/classic
    verdict, but this replica's handler is the OTHER kind (a same-name
    redeploy swapped the deployment type). Raised BEFORE user code runs,
    so the proxy can safely retry with the full request."""

    # The proxy sees remote errors as flattened TaskError text, so it
    # matches this token rather than the class name — a user exception
    # merely MENTIONING "VerdictMismatch" must not trigger a retry
    # (requests may be non-idempotent).
    TOKEN = "__ray_tpu_verdict_mismatch__"

    def __init__(self, deployment_name: str):
        super().__init__(f"{self.TOKEN} {deployment_name}")


def _check_trim(req, callable_obj, deployment_name: str) -> None:
    """Pop the proxy's __trim__ marker and refuse (before user code
    runs) if the learned verdict no longer matches this handler's
    kind."""
    if isinstance(req, dict) and "__trim__" in req:
        trim = req.pop("__trim__")
        handler_is_asgi = hasattr(callable_obj, "__serve_asgi_app__")
        if (trim == "asgi") != handler_is_asgi:
            raise VerdictMismatch(deployment_name)


class Replica:
    """User-code host (reference: replica.py UserCallableWrapper)."""

    def __init__(self, cls_blob: bytes, init_args: tuple,
                 init_kwargs: dict, deployment_name: str,
                 user_config: Optional[Any] = None):
        import cloudpickle
        target = cloudpickle.loads(cls_blob)
        self._deployment_name = deployment_name
        self._ongoing = 0
        if inspect.isclass(target):
            self._callable = target(*init_args, **init_kwargs)
        else:
            # Function deployment: the function IS the request handler.
            self._callable = target
        if user_config is not None:
            self._apply_user_config(user_config)

    def _apply_user_config(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is None:
            raise ValueError(
                f"Deployment {self._deployment_name} passed user_config but "
                "its class defines no reconfigure(user_config) method")
        fn(user_config)

    async def handle_request(self, method_name: str, args: tuple,
                             kwargs: dict,
                             multiplexed_model_id: str = "") -> Any:
        """Run one request through the user callable.

        Sync user code is offloaded to a thread so the replica's event loop
        keeps serving concurrent requests (reference fibers/asyncio model:
        replica.py + transport/fiber.h).
        """
        from ..multiplex import _set_request_model_id
        self._ongoing += 1
        t0 = None
        if telemetry.enabled:
            # Replica-side dispatch metrics: these live in the worker
            # process's registry and reach the head via the piggybacked
            # METRICS_PUSH (telemetry.py metric federation).
            t0 = time.monotonic()
            telemetry.serve_replica_ongoing(self._deployment_name,
                                            self._ongoing)
        _set_request_model_id(multiplexed_model_id)
        try:
            # Proxy HTTP requests carry a __trim__ marker when a learned
            # verdict dropped one half of the request payload. If the
            # verdict no longer matches this replica's handler kind (a
            # same-name redeploy swapped ASGI <-> classic), refuse
            # BEFORE running user code: the proxy drops its verdict and
            # retries once with the full request — no side effects run
            # twice and no stale-verdict 500 loop forms.
            if args:
                _check_trim(args[0], self._callable,
                            self._deployment_name)
            if inspect.isfunction(self._callable) or inspect.ismethod(
                    self._callable) or not hasattr(
                        self._callable, method_name):
                target = self._callable  # function deployment
            else:
                target = getattr(self._callable, method_name)
            if inspect.isgeneratorfunction(target) or \
                    inspect.isasyncgenfunction(target):
                # Statically streaming: refuse BEFORE executing so the
                # streaming retry doesn't double-run side effects.
                raise StreamingResponseRequired(self._deployment_name)
            if inspect.iscoroutinefunction(target):
                result = await target(*args, **kwargs)
            else:
                import contextvars
                # ctx.run: the executor thread must see the request's
                # multiplexed model id (run_in_executor does not
                # propagate contextvars by itself).
                ctx = contextvars.copy_context()
                result = await asyncio.get_event_loop().run_in_executor(
                    None, lambda: ctx.run(target, *args, **kwargs))
            if inspect.isgenerator(result) or inspect.isasyncgen(result):
                # Caller used the non-streaming path on a handler that
                # DYNAMICALLY returned a generator; tell it to retry via
                # handle_request_streaming (the proxy caches the verdict
                # per deployment). KNOWN LIMITATION: the handler body has
                # already run once here, so side effects execute twice
                # for this one transition request — same as the
                # reference's requirement that streaming handlers be
                # declared, minus the declaration. Statically detectable
                # generators are refused before execution above.
                raise StreamingResponseRequired(self._deployment_name)
            return result
        finally:
            self._ongoing -= 1
            if t0 is not None:
                telemetry.serve_replica_request(self._deployment_name,  # lint: ungated-instrumentation-ok t0 is non-None only when telemetry.enabled was set at entry
                                                time.monotonic() - t0)
                telemetry.serve_replica_ongoing(self._deployment_name,  # lint: ungated-instrumentation-ok t0 gate, as above
                                                self._ongoing)

    def _resolve_target(self, method_name: str):
        if inspect.isfunction(self._callable) or inspect.ismethod(
                self._callable) or not hasattr(self._callable,
                                               method_name):
            return self._callable  # function deployment
        return getattr(self._callable, method_name)

    def handle_request_streaming(self, method_name: str, args: tuple,
                                 kwargs: dict,
                                 multiplexed_model_id: str = ""):
        """Generator variant of handle_request (reference: streaming
        responses through the proxy, serve/_private/replica.py
        call_user_generator). First yielded item is a marker dict so the
        consumer knows whether the user returned a stream or one value;
        user generators then stream item by item over GEN_ITEM messages.
        """
        import contextvars

        from ..multiplex import _set_request_model_id
        self._ongoing += 1
        # Per-REQUEST context: two interleaved streaming requests share
        # this thread, so the model id must live in a context copied
        # for this generator — user code calling
        # serve.get_multiplexed_model_id() after the first yield must
        # never read the OTHER request's id.
        req_ctx = contextvars.copy_context()
        try:
            def _start():
                _set_request_model_id(multiplexed_model_id)
                # Same mismatch refusal as the unary path: a stream-mode
                # deployment swapped to the other kind by a same-name
                # redeploy must not silently run on a trimmed request.
                if args:
                    _check_trim(args[0], self._callable,
                                self._deployment_name)
                target = self._resolve_target(method_name)
                result = target(*args, **kwargs)
                if inspect.iscoroutine(result):
                    result = asyncio.run(result)
                return result

            result = req_ctx.run(_start)
            if inspect.isgenerator(result):
                yield {"__stream__": True}
                try:
                    while True:
                        try:
                            item = req_ctx.run(next, result)
                        except StopIteration:
                            break
                        yield item
                finally:
                    # An abandoned stream (consumer close ->
                    # GeneratorExit at the yield above) must close the
                    # USER generator now so its finally/context-manager
                    # cleanup runs deterministically, as `yield from`
                    # would have done.
                    try:
                        req_ctx.run(result.close)
                    except Exception:
                        pass
            else:
                yield {"__stream__": False}
                yield result
        finally:
            self._ongoing -= 1

    async def get_queue_len(self) -> int:
        """Power-of-two probe (reference: replica scheduler queue-length
        probes, pow_2_scheduler.py:52)."""
        return self._ongoing

    async def get_queue_len_and_models(self) -> tuple:
        """Combined probe: (queue length, multiplexed model ids loaded
        here). Routers use the ids for model-aware routing (reference:
        pow_2_scheduler's multiplexed ranking via controller-pushed
        model ids — here the info rides the existing probe instead)."""
        from ..multiplex import loaded_model_ids
        return self._ongoing, loaded_model_ids(self._callable)

    async def reconfigure(self, user_config) -> bool:
        self._apply_user_config(user_config)
        return True

    async def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.isawaitable(out):
                out = await out
            return bool(out) if out is not None else True
        return True

    async def prepare_shutdown(self) -> bool:
        fn = getattr(self._callable, "__del__", None)
        return True


def start_replica(deployment_name: str, replica_idx: int, cls_blob: bytes,
                  init_args: tuple, init_kwargs: dict,
                  actor_options: Dict[str, Any],
                  max_ongoing_requests: int,
                  user_config: Optional[Any] = None):
    """Spawn one replica actor (reference: deployment_state.py
    _start_replica)."""
    opts = dict(actor_options)
    opts.setdefault("name", f"SERVE_REPLICA::{deployment_name}#{replica_idx}")
    opts["max_concurrency"] = max(int(max_ongoing_requests) * 2, 16)
    return ray_tpu.remote(Replica).options(**opts).remote(
        cls_blob, init_args, init_kwargs, deployment_name, user_config)
