"""Serve gRPC ingress.

Reference parity: the reference runs a gRPC proxy next to the HTTP proxy
(serve/_private/proxy.py gRPCProxy; user protos registered via
grpc_options). This build serves a GENERIC unary interface instead of
user-compiled protobuf servicers: requests address
`/<app_name>/<method_name>` with a pickled `{"args": [...], "kwargs":
{...}}` payload and receive the pickled return value — the same
deployment-handle routing path as HTTP, minus protoc codegen. Use
`GrpcServeClient` for the matching client side.

SECURITY: the payload is pickle — deserializing attacker bytes is code
execution. The proxy therefore binds loopback only unless the caller
passes `allow_remote=True` and owns the network boundary (the reference
gRPC proxy has the same trust model: protobuf there, but handlers run
arbitrary user code either way).
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Optional, Tuple

from . import dispatch as _dispatch
from .direct_client import ReplicaQueueFullError, ReplicaUnavailableError

_HANDLE_TTL_S = 5.0    # re-resolve app handles (delete/redeploy safety)
_MISS_TTL_S = 1.0      # negative cache: throttle route-miss controller RPCs


class GRPCProxy:
    """Generic unary-unary gRPC front (reference: proxy.py gRPCProxy)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16, request_timeout_s: float = 30.0,
                 allow_remote: bool = False):
        if not allow_remote and host not in ("127.0.0.1", "localhost",
                                             "::1"):
            raise ValueError(
                f"GRPCProxy binds loopback only (got host={host!r}): the "
                "wire format is pickle, so exposing it beyond localhost "
                "is remote code execution for anyone who can reach the "
                "port. Pass allow_remote=True only behind a trusted "
                "network boundary.")
        import grpc
        from concurrent import futures
        self._timeout_s = request_timeout_s
        # app/method -> (handle, expires_at); misses -> (None, expires_at)
        self._handles: dict = {}
        self._lock = threading.Lock()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers,
                                       thread_name_prefix="serve-grpc"),
            handlers=(self._make_handler(),))
        self.host = host
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    class _RouteMiss(Exception):
        pass

    def _handle_for(self, app: str, method: str):
        """TTL-cached handle resolution: handles go stale on
        delete/redeploy, and a route-miss must not hammer the
        controller (the HTTP proxy throttles its refresh the same
        way)."""
        key = (app, method)
        now = time.monotonic()
        with self._lock:
            entry = self._handles.get(key)
        if entry is not None and entry[1] > now:
            if entry[0] is None:
                raise GRPCProxy._RouteMiss(app)
            return entry[0]
        from .. import get_app_handle
        try:
            h = get_app_handle(app)
        except ValueError:
            with self._lock:
                self._handles[key] = (None, now + _MISS_TTL_S)
            raise GRPCProxy._RouteMiss(app) from None
        if method != "__call__":
            h = h.options(method_name=method)
        with self._lock:
            old = self._handles.get(key)
            self._handles[key] = (h, now + _HANDLE_TTL_S)
        if old is not None and old[0] is not None and old[0] is not h:
            _shutdown_handle(old[0])
        return h

    def _make_handler(self):
        import grpc
        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, details):
                path = details.method  # "/<app>/<method>"

                def unary(request: bytes, context):
                    app, _, method = path.strip("/").partition("/")
                    try:
                        payload = pickle.loads(request) if request else {}
                        handle = proxy._handle_for(app, method or
                                                   "__call__")
                        args = tuple(payload.get("args", ()))
                        kwargs = payload.get("kwargs", {})
                        # Same dispatch helper as the HTTP proxy: the
                        # direct data plane, the load-aware claim, and
                        # the shed decision must not fork per protocol.
                        resp = _dispatch.try_direct(handle, args,
                                                    kwargs)
                        if resp is None:
                            resp = handle.remote(*args, **kwargs)
                        value = resp.result(timeout_s=proxy._timeout_s)
                        return pickle.dumps(value)
                    except GRPCProxy._RouteMiss:
                        context.abort(grpc.StatusCode.NOT_FOUND,
                                      f"no application named {app!r}")
                    except ReplicaQueueFullError as e:
                        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                      repr(e))
                    except ReplicaUnavailableError as e:
                        context.abort(grpc.StatusCode.UNAVAILABLE,
                                      repr(e))
                    except Exception as e:  # noqa: BLE001 — map to status
                        context.abort(grpc.StatusCode.INTERNAL, repr(e))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,   # raw bytes in
                    response_serializer=None)    # raw bytes out

        return _Generic()

    def stop(self):
        self._server.stop(grace=1.0)
        with self._lock:
            handles, self._handles = self._handles, {}
        for h, _ in handles.values():
            if h is not None:
                _shutdown_handle(h)


def _shutdown_handle(handle):
    """Stop a handle's router/long-poll thread (leak-free teardown)."""
    try:
        handle.shutdown()
    except Exception:
        pass


class GrpcServeClient:
    """Client for the generic proxy: call(app, *args, method=..., **kw).
    (reference: users generate protobuf stubs; this pairs with the
    generic ingress above.)"""

    def __init__(self, address: str, timeout_s: float = 30.0):
        import grpc
        self._channel = grpc.insecure_channel(address)
        self._timeout_s = timeout_s

    def call(self, app: str, *args, method: str = "__call__",
             **kwargs) -> Any:
        fn = self._channel.unary_unary(
            f"/{app}/{method}",
            request_serializer=None, response_deserializer=None)
        payload = pickle.dumps({"args": args, "kwargs": kwargs})
        return pickle.loads(fn(payload, timeout=self._timeout_s))

    def close(self):
        self._channel.close()


_grpc_proxy: Optional[GRPCProxy] = None


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0,
                     **kwargs) -> GRPCProxy:
    """Start (or return) the process-wide gRPC proxy next to the HTTP
    one (reference: serve.start(grpc_options=...)). Re-calling with a
    conflicting address errors instead of silently returning the old
    binding."""
    global _grpc_proxy
    if _grpc_proxy is not None:
        if (host not in ("127.0.0.1", _grpc_proxy.host)
                or (port not in (0, _grpc_proxy.port))):
            raise RuntimeError(
                f"gRPC proxy already running on {_grpc_proxy.host}:"
                f"{_grpc_proxy.port}; call serve.shutdown() before "
                f"rebinding to {host}:{port}.")
        return _grpc_proxy
    _grpc_proxy = GRPCProxy(host, port, **kwargs)
    return _grpc_proxy


def stop_grpc_proxy():
    global _grpc_proxy
    if _grpc_proxy is not None:
        _grpc_proxy.stop()
        _grpc_proxy = None
