"""Long-poll config broadcast.

Reference: python/ray/serve/_private/long_poll.py — LongPollHost (:204)
held by the controller publishes keyed snapshots; LongPollClient (:66)
blocks on `listen_for_change(snapshot_ids)` and wakes when any watched
key advances. Here the host is plain asyncio state inside the async
controller actor; clients run a daemon thread of repeated long-poll actor
calls (the control plane stays off the TPU data path entirely).
"""
import asyncio
import threading
from typing import Any, Callable, Dict


class LongPollHost:
    """Keyed snapshot store with async change notification."""

    def __init__(self):
        self._snapshots: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}
        self._event = asyncio.Event()

    def notify_changed(self, key: str, snapshot: Any):
        self._snapshots[key] = snapshot
        self._versions[key] = self._versions.get(key, 0) + 1
        self._event.set()

    async def listen_for_change(self, snapshot_ids: Dict[str, int],
                                timeout_s: float = 30.0) -> Dict[str, Any]:
        """Return {key: (version, snapshot)} for every watched key whose
        version is newer than the client's; block (up to timeout) when
        nothing changed.  Empty dict on timeout — the client just re-polls.
        """
        deadline = asyncio.get_event_loop().time() + timeout_s
        while True:
            updates = {
                key: (self._versions[key], self._snapshots[key])
                for key, seen in snapshot_ids.items()
                if self._versions.get(key, 0) > seen
            }
            if updates:
                return updates
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                return {}
            self._event.clear()
            try:
                await asyncio.wait_for(self._event.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                return {}


class LongPollClient:
    """Daemon-thread client: watches keys on the controller handle and
    invokes callbacks with fresh snapshots (reference: long_poll.py:66)."""

    def __init__(self, controller_handle,
                 key_listeners: Dict[str, Callable[[Any], None]]):
        self._controller = controller_handle
        self._listeners = dict(key_listeners)
        self._snapshot_ids = {k: 0 for k in key_listeners}
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-long-poll")
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        import ray_tpu
        while not self._stopped.is_set():
            try:
                updates = ray_tpu.get(
                    self._controller.listen_for_change.remote(
                        self._snapshot_ids, 5.0),
                    timeout=60.0)
            except Exception:
                if self._stopped.is_set():
                    return
                self._stopped.wait(0.5)
                continue
            for key, (version, snapshot) in (updates or {}).items():
                self._snapshot_ids[key] = version
                try:
                    self._listeners[key](snapshot)
                except Exception:
                    pass
