"""HTTP proxy: routes requests to application ingress deployments.

Reference: python/ray/serve/_private/proxy.py:752 (HTTPProxy),
proxy_request (:418) — per-node proxy matching routes by longest prefix
and forwarding to a DeploymentHandle; the route table is pushed from the
controller over long-poll.

Implementation: a ThreadingHTTPServer in the driver process (stdlib-only;
the image bakes no ASGI server). Each request thread blocks on the
handle's DeploymentResponse, which is fine — the proxy is control-plane;
replica compute is where TPU time goes.
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from .long_poll import LongPollClient


class _ProxyState:
    def __init__(self, controller):
        self._controller = controller
        self._routes: Dict[str, tuple] = {}
        self._handles: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        self._long_poll = LongPollClient(
            controller, {"routes": self._update_routes})
        import ray_tpu
        try:
            self._update_routes(
                ray_tpu.get(controller.get_route_table.remote()))
        except Exception:
            pass

    def _update_routes(self, routes: Dict[str, tuple]):
        with self._lock:
            self._routes = dict(routes or {})

    def match(self, path: str) -> Optional[tuple]:
        """Longest-prefix route match (reference: proxy.py route matching).
        A miss refreshes the table synchronously once before giving up —
        a request can legally arrive before the long-poll delivers a
        just-deployed app's routes."""
        target = self._match_locked(path)
        if target is not None:
            return target
        # Throttled: unmatched-path floods must not turn every 404 into
        # a controller RPC (one refresh per second serves the
        # just-deployed-app race without the amplification).
        import time as _time

        import ray_tpu
        with self._lock:
            now = _time.monotonic()
            if now - self._last_refresh < 1.0:
                return None
            self._last_refresh = now
        try:
            self._update_routes(
                ray_tpu.get(self._controller.get_route_table.remote(),
                            timeout=10))
        except Exception:
            return None
        return self._match_locked(path)

    def _match_locked(self, path: str) -> Optional[tuple]:
        with self._lock:
            best = None
            for prefix, target in self._routes.items():
                norm = prefix.rstrip("/") or "/"
                if path == norm or path.startswith(
                        norm if norm.endswith("/") else norm + "/") \
                        or norm == "/":
                    if best is None or len(norm) > len(best[0]):
                        best = (norm, target)
            return best[1] if best else None

    def handle_for(self, deployment: str, app: str):
        with self._lock:
            h = self._handles.get(deployment)
        if h is None:
            from ..handle import DeploymentHandle
            h = DeploymentHandle(deployment, app)
            with self._lock:
                self._handles[deployment] = h
        return h

    def stop(self):
        self._long_poll.stop()


def _make_handler(proxy_state: _ProxyState):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # silence per-request stderr spam
            pass

        def _respond(self, code: int, body, content_type="application/json"):
            if isinstance(body, (dict, list)):
                payload = json.dumps(body).encode()
            elif isinstance(body, str):
                payload = body.encode()
                content_type = "text/plain"
            elif isinstance(body, bytes):
                payload = body
                content_type = "application/octet-stream"
            else:
                payload = json.dumps({"result": repr(body)}).encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _serve(self):
            if self.path == "/-/healthz":
                return self._respond(200, "success")
            if self.path == "/-/routes":
                with proxy_state._lock:
                    return self._respond(
                        200, {p: t[0] for p, t in
                              proxy_state._routes.items()})
            target = proxy_state.match(self.path.split("?")[0])
            if target is None:
                return self._respond(404, {"error": "no route"})
            app, deployment = target
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            try:
                body = json.loads(raw) if raw else None
            except Exception:
                body = raw.decode(errors="replace")
            request = {"path": self.path, "method": self.command,
                       "body": body}
            try:
                handle = proxy_state.handle_for(deployment, app)
                rg = handle.options(stream=True).remote(request)
                if not rg.is_stream(timeout_s=60.0):
                    return self._respond(200,
                                         rg.single_result(timeout_s=60.0))
            except Exception as e:
                return self._respond(500, {"error": str(e)})
            # Chunked transfer: one chunk per generator item (reference:
            # streaming responses through the proxy, proxy.py over ASGI).
            # Headers are already on the wire once streaming starts, so a
            # mid-stream failure can only truncate the chunked body (no
            # terminating 0-chunk) — never emit a second status line.
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                for item in rg:
                    chunk = item if isinstance(item, bytes) else (
                        item if isinstance(item, str)
                        else json.dumps(item)).encode()
                    self.wfile.write(
                        f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                self.wfile.write(b"0\r\n\r\n")
            except Exception:
                self.close_connection = True

        do_GET = do_POST = do_PUT = do_DELETE = _serve

    return Handler


class HTTPProxy:
    """Proxy server lifecycle (reference: proxy.py HTTPProxy)."""

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 8000):
        self._state = _ProxyState(controller)
        self._server = ThreadingHTTPServer(
            (host, port), _make_handler(self._state))
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http-proxy")
        self._thread.start()

    def stop(self):
        self._state.stop()
        self._server.shutdown()
        self._server.server_close()
