"""HTTP proxy: async event-loop server routing to ingress deployments.

Reference: python/ray/serve/_private/proxy.py:752 (HTTPProxy) — an ASGI
event-loop proxy, NOT a thread-per-request server; proxy_request (:418)
matches routes by longest prefix and forwards to a DeploymentHandle; the
route table is pushed from the controller over long-poll.

Implementation: aiohttp web server on a dedicated event loop.
Request handling is fully async — the handle's DeploymentResponse is
awaited (ObjectRef.__await__), so thousands of in-flight requests cost
coroutines, not threads, and slow replicas exert natural backpressure on
the loop instead of unbounded thread growth (the round-1
ThreadingHTTPServer weakness)."""
import asyncio
import json
import threading
import time
from typing import Dict, Optional

from ray_tpu._private import telemetry
from ray_tpu.util import tracing
from . import dispatch as _dispatch
from .direct_client import ReplicaQueueFullError, ReplicaUnavailableError
from .long_poll import LongPollClient


class _ProxyState:
    def __init__(self, controller, on_routes_changed=None):
        self._controller = controller
        self._routes: Dict[str, tuple] = {}
        self._handles: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        self._on_routes_changed = on_routes_changed
        self._long_poll = LongPollClient(
            controller, {"routes": self._update_routes})
        import ray_tpu
        try:
            self._update_routes(
                ray_tpu.get(controller.get_route_table.remote()))
        except Exception:
            pass

    def _update_routes(self, routes: Dict[str, tuple]):
        with self._lock:
            changed = self._routes != dict(routes or {})
            self._routes = dict(routes or {})
        if changed and self._on_routes_changed is not None:
            # Table changed: deployments may be new types — forget the
            # learned verdicts. (A same-name redeploy leaves the table
            # identical; that case self-corrects response-side — the
            # proxy re-learns the verdict from every response.)
            self._on_routes_changed()

    def match(self, path: str) -> Optional[tuple]:
        """Longest-prefix route match (reference: proxy.py route matching).
        A miss refreshes the table synchronously once before giving up —
        a request can legally arrive before the long-poll delivers a
        just-deployed app's routes."""
        target = self._match_locked(path)
        if target is not None:
            return target
        # Throttled: unmatched-path floods must not turn every 404 into
        # a controller RPC (one refresh per second serves the
        # just-deployed-app race without the amplification).
        import time as _time

        import ray_tpu
        with self._lock:
            now = _time.monotonic()
            if now - self._last_refresh < 1.0:
                return None
            self._last_refresh = now
        try:
            self._update_routes(
                ray_tpu.get(self._controller.get_route_table.remote(),
                            timeout=10))
        except Exception:
            return None
        return self._match_locked(path)

    def _match_locked(self, path: str) -> Optional[tuple]:
        with self._lock:
            best = None
            for prefix, target in self._routes.items():
                norm = prefix.rstrip("/") or "/"
                if path == norm or path.startswith(
                        norm if norm.endswith("/") else norm + "/") \
                        or norm == "/":
                    if best is None or len(norm) > len(best[0]):
                        best = (norm, target)
            # (app_name, deployment, matched_prefix) — the prefix rides
            # to ASGI ingress deployments as the root_path.
            return (best[1][0], best[1][1], best[0]) if best else None

    def handle_for(self, deployment: str, app: str):
        with self._lock:
            h = self._handles.get(deployment)
        if h is None:
            from ..handle import DeploymentHandle
            h = DeploymentHandle(deployment, app)
            with self._lock:
                self._handles[deployment] = h
        return h

    def stop(self):
        self._long_poll.stop()


def _in_executor(loop, fn):
    """run_in_executor carrying the caller's contextvars: the active
    trace span must reach the submit path (stdlib run_in_executor does
    not propagate context by itself)."""
    import contextvars
    ctx = contextvars.copy_context()
    return loop.run_in_executor(None, lambda: ctx.run(fn))


def _to_web_response(result):
    """Translate a replica result into an aiohttp response. ASGI
    ingress envelopes replay the app's real status/headers/body;
    anything else goes through the classic body encoding."""
    from aiohttp import web
    if isinstance(result, dict) and result.get("__asgi__"):
        resp = web.Response(body=result.get("body", b""),
                            status=int(result.get("status", 200)))
        for k, v in result.get("headers", []):
            lk = k.lower()
            if lk in ("content-length", "transfer-encoding"):
                continue  # aiohttp recomputes framing headers
            if lk == "content-type":
                resp.headers[k] = v  # single-valued by construction
            else:
                # add(), not assignment: repeatable headers (multiple
                # Set-Cookie) must all reach the client.
                resp.headers.add(k, v)
        return resp
    payload, ctype = _encode_body(result)
    return web.Response(body=payload, content_type=ctype)


def _encode_body(body):
    if isinstance(body, (dict, list)):
        return json.dumps(body).encode(), "application/json"
    if isinstance(body, str):
        return body.encode(), "text/plain"
    if isinstance(body, bytes):
        return body, "application/octet-stream"
    return json.dumps({"result": repr(body)}).encode(), "application/json"


class HTTPProxy:
    """Async proxy server lifecycle (reference: proxy.py HTTPProxy)."""

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 8000):
        self._modes: Dict[str, str] = {}  # deployment -> unary | stream
        # deployment -> True (ASGI ingress) | False (classic handler);
        # absent until the first response teaches us which half of the
        # request envelope the deployment consumes.
        self._asgi: Dict[tuple, bool] = {}
        self._state = _ProxyState(
            controller, on_routes_changed=self._forget_learned)
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._start_error = None
        self.host, self.port = host, port
        self._runner = None
        self._thread = threading.Thread(
            target=self._run, args=(host, port), daemon=True,
            name="serve-http-proxy")
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("serve proxy failed to start in 30s")
        if self._start_error is not None:
            raise self._start_error

    def _forget_learned(self):
        self._modes.clear()
        self._asgi.clear()

    # -- server thread -------------------------------------------------
    def _run(self, host: str, port: int):
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._start(host, port))
        except BaseException as e:  # surface bind errors to __init__
            self._start_error = e
            self._started.set()
            self._loop.close()
            return
        self._loop.run_forever()
        self._loop.run_until_complete(self._loop.shutdown_asyncgens())
        self._loop.close()

    async def _start(self, host: str, port: int):
        from aiohttp import web
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        for s in self._runner.sites:
            sock = s._server.sockets[0]
            self.host, self.port = sock.getsockname()[:2]
            break
        self._started.set()

    async def _handle(self, request):
        """Tracing entry: when tracing is on (or the client sent a W3C
        ``traceparent``), the request runs under a ``serve.request``
        span whose context the replica dispatch inherits through the
        task spec — proxy → replica → nested-task spans form ONE tree —
        and the response echoes the span's ``traceparent`` back
        (reference: the reference proxy's OTel middleware). One module
        attr + one header probe when tracing is off."""
        tp = request.headers.get("traceparent")
        if not tracing.enabled and tp is None:
            return await self._handle_instrumented(request)
        token = None
        try:
            ctx = tracing.parse_traceparent(tp)
            token = tracing.activate_context(ctx)  # lint: ungated-instrumentation-ok gated by the tracing.enabled-or-traceparent check above
            cur = None
            with tracing.span("serve.request", method=request.method,  # lint: ungated-instrumentation-ok same gate
                              path=request.path):
                cur = tracing.current_context()
                resp = await self._handle_instrumented(request)
            if cur is not None:
                try:
                    resp.headers["traceparent"] = \
                        tracing.format_traceparent(
                            cur["trace_id"], cur["parent_span_id"])
                except Exception:
                    pass  # prepared/streaming response: headers sent
            return resp
        finally:
            tracing.deactivate_context(token)

    async def _handle_instrumented(self, request):
        """Telemetry entry: request-latency histogram + in-flight
        gauge per deployment from the telemetry plane (reference:
        serve_num_http_requests / processing-latency metrics on the
        proxy). One falsy-flag check when telemetry is off; the route
        is matched ONCE here and handed to the inner handler (matching
        twice would double the lock + table scan per request and could
        mislabel pre-long-poll requests as unmatched)."""
        if not telemetry.enabled:
            return await self._handle_inner(request)
        path = request.path
        if path in ("/-/healthz", "/-/routes"):
            return await self._handle_inner(request)
        target = self._state.match(path)
        dep = target[1] if target else "_unmatched"
        t0 = time.monotonic()
        telemetry.serve_inflight(dep, 1)  # lint: ungated-instrumentation-ok gated by the early return above; telemetry-off requests never reach here
        try:
            return await self._handle_inner(request, target)
        finally:
            telemetry.serve_inflight(dep, -1)  # lint: ungated-instrumentation-ok gated by the early return above
            telemetry.serve_request(dep, time.monotonic() - t0)  # lint: ungated-instrumentation-ok gated by the early return above

    async def _handle_inner(self, request, _target=None):
        from aiohttp import web
        path = request.path
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            with self._state._lock:
                return web.json_response(
                    {p: t[0] for p, t in self._state._routes.items()})
        target = _target if _target is not None \
            else self._state.match(path)
        if target is None:
            return web.json_response({"error": "no route"}, status=404)
        app_name, deployment, matched_prefix = target
        if request.headers.get("Upgrade", "").lower() == "websocket":
            return await self._handle_ws(request, app_name, deployment,
                                         matched_prefix)
        raw = await request.read()
        # Learned per deployment from its first response: ASGI ingress
        # deployments consume the raw bytes + headers and ignore the
        # decoded body; classic handlers are the reverse. Shipping both
        # would double the serialized payload on every request, so
        # until the first response both ride, then only one does.
        mode_key = (app_name, deployment)
        is_asgi = self._asgi.get(mode_key)

        def _build_req(verdict):
            """One request dict, trimmed per the learned verdict.
            verdict None ships BOTH halves (first contact / retry)."""
            r = {"path": request.path_qs, "method": request.method,
                 "body": None, "route_prefix": matched_prefix}
            if verdict is not True:  # classic half: decoded body
                try:
                    r["body"] = json.loads(raw) if raw else None
                except Exception:
                    r["body"] = raw.decode(errors="replace")
            if verdict is not False:  # ASGI half: raw bytes + headers
                r["raw_body"] = raw
                r["headers"] = [(k, v)
                                for k, v in request.headers.items()]
                # Undecoded path+query for the ASGI half: path_qs is
                # percent-DECODED by yarl, which would corrupt encoded
                # metacharacters (%26 etc.) before the app's query
                # parser.
                r["raw_path"] = request.raw_path
            if verdict is not None:
                # Lets the replica refuse a mismatched trim BEFORE user
                # code runs (same-name redeploy swapping the type).
                r["__trim__"] = "asgi" if verdict else "classic"
            return r

        req = _build_req(is_asgi)
        handle = self._state.handle_for(deployment, app_name)
        # Model multiplexing header (reference: proxy.py reading
        # SERVE_MULTIPLEXED_MODEL_ID from the request) — routed
        # model-aware, surfaced via serve.get_multiplexed_model_id().
        model_id = request.headers.get("serve_multiplexed_model_id", "")
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        loop = asyncio.get_running_loop()
        # Unary fast path: one plain actor call instead of the streaming
        # generator machinery (3 messages + 2 result waits). The replica
        # raises StreamingResponseRequired when the handler actually
        # streams; the verdict is cached per deployment.
        mode = self._modes.get(mode_key, "unary")
        if mode == "unary":
            # Up to two attempts: the replica raises VerdictMismatch —
            # BEFORE running user code — when the learned verdict
            # trimmed the request but a same-name redeploy swapped the
            # deployment's kind (ASGI <-> classic). Drop the verdict and
            # resend the full request exactly once. Genuine handler
            # errors are NOT retried (requests may be non-idempotent).
            for attempt in (0, 1):
                try:
                    # Direct data plane first: least-loaded claim +
                    # SERVE_REQ on the replica's brokered channel (the
                    # head never sees the request). None = not
                    # available yet (flag off, channel establishing):
                    # fall through to the classic handle path. A full
                    # queue sheds 503 HERE — admission control must
                    # not quietly retry through the head.
                    try:
                        resp = _dispatch.try_direct(handle, (req,), {})
                    except ReplicaQueueFullError as e:
                        if telemetry.enabled:
                            telemetry.serve_shed(deployment)  # lint: ungated-instrumentation-ok gated by the telemetry.enabled check above
                        return web.json_response({"error": str(e)},
                                                 status=503)
                    # Fast path: when replicas are ready and probes
                    # fresh, assignment cannot block — submit inline and
                    # skip the executor hop. Otherwise assign_request
                    # can block (replica ready-wait, queue probes): keep
                    # it off the event loop. The response await is
                    # callback-based either way.
                    if resp is None:
                        resp = handle._remote_fast(req)
                    if resp is None:
                        resp = await _in_executor(
                            loop, lambda: handle.remote(req))
                    result = await resp
                    # ALWAYS refresh from the response (not just when
                    # unknown): a same-name redeploy swapping the
                    # deployment type leaves the route table identical,
                    # so this is the invalidation path — one degraded
                    # request, then the verdict is right again.
                    got_asgi = bool(isinstance(result, dict)
                                    and result.get("__asgi__"))
                    if self._asgi.get(mode_key) != got_asgi:
                        self._asgi[mode_key] = got_asgi
                    return _to_web_response(result)
                except Exception as e:
                    # TaskError carries the remote class name in its
                    # message.
                    if "StreamingResponseRequired" in f"{e!r}{e}":
                        self._modes[mode_key] = "stream"
                        self._asgi.setdefault(mode_key, False)
                        break
                    if (attempt == 0
                            and "__ray_tpu_verdict_mismatch__"
                            in f"{e!r}{e}"):
                        self._asgi.pop(mode_key, None)
                        req = _build_req(None)
                        continue
                    if isinstance(e, ReplicaUnavailableError):
                        # Channel died mid-request (replica SIGKILL):
                        # typed 503, never a hang — the controller will
                        # restart the replica and the next request
                        # re-establishes.
                        return web.json_response({"error": str(e)},
                                                 status=503)
                    return web.json_response({"error": str(e)},
                                             status=500)
        try:
            rg = await _in_executor(
                loop, lambda: handle.options(stream=True).remote(req))
            # is_stream blocks on the first generator item; keep the
            # event loop free.
            is_stream = await loop.run_in_executor(
                None, lambda: rg.is_stream(timeout_s=60.0))
            if not is_stream:
                result = await loop.run_in_executor(
                    None, lambda: rg.single_result(timeout_s=60.0))
                return _to_web_response(result)
        except Exception as e:
            if "__ray_tpu_verdict_mismatch__" in f"{e!r}{e}":
                # Stream-mode deployment swapped kind by a same-name
                # redeploy: forget both learned verdicts and re-handle
                # from scratch. Bounded: the rebuilt request ships both
                # halves with no trim marker, so a second mismatch is
                # impossible.
                self._modes.pop(mode_key, None)
                self._asgi.pop(mode_key, None)
                return await self._handle_inner(request)
            return web.json_response({"error": str(e)}, status=500)
        # Streaming: one chunk per generator item (reference: streaming
        # responses through the proxy over ASGI).
        resp = web.StreamResponse()
        resp.content_type = "text/plain"
        await resp.prepare(request)
        it = iter(rg)

        def _next():
            try:
                return next(it)
            except StopIteration:
                return _SENTINEL
        try:
            while True:
                item = await asyncio.get_running_loop().run_in_executor(
                    None, _next)
                if item is _SENTINEL:
                    break
                if isinstance(item, bytes):
                    chunk = item
                elif isinstance(item, str):
                    chunk = item.encode()
                else:
                    chunk = json.dumps(item).encode()
                await resp.write(chunk)
        except Exception:
            pass  # mid-stream failure: truncate, never a second status
        await resp.write_eof()
        return resp

    async def _handle_ws(self, request, app_name: str, deployment: str,
                         matched_prefix: str):
        """Websocket pass-through (reference: proxy.py:418 carrying
        websocket ASGI scopes): pin ONE replica for the connection's
        lifetime (pick_sticky), open the app's websocket cycle there,
        pump outbound events from a streaming call, and feed client
        frames as ordered actor calls. The upgrade is accepted before
        the app runs; an app that closes without accepting just closes
        the socket."""
        import asyncio
        import uuid

        from aiohttp import WSMsgType, web

        from ..handle import DeploymentResponseGenerator

        loop = asyncio.get_running_loop()
        handle = self._state.handle_for(deployment, app_name)
        try:
            router = await loop.run_in_executor(None, handle._get_router)
            replica, release = await loop.run_in_executor(
                None, router.pick_sticky)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=503)
        conn_id = uuid.uuid4().hex
        req = {"path": request.path_qs,
               "raw_path": request.raw_path,
               "route_prefix": matched_prefix,
               "headers": [(k, v) for k, v in request.headers.items()]}
        ws = web.WebSocketResponse()
        opened = False
        seq = 0  # before any fallible step: the finally's ws_close uses it
        try:
            # Inside the release-guard: a client that resets between
            # the Upgrade request and prepare() must not leak the
            # sticky in-flight count.
            await ws.prepare(request)
            ok = await replica.handle_request.remote(
                "ws_open", (conn_id, req), {}, "")
            opened = True
            if not ok:
                await ws.close()
                return ws
            raw_gen = replica.handle_request_streaming.options(
                num_returns="streaming").remote(
                    "ws_stream", (conn_id,), {}, "")
            rg = DeploymentResponseGenerator(raw_gen)
            it = iter(rg)

            def _next():
                try:
                    return next(it)
                except StopIteration:
                    return _SENTINEL

            async def _pump_out():
                # try/finally: a replica death mid-stream (next(it)
                # raises) or a send failure must still close the
                # client socket — otherwise the client waits forever
                # for frames that will never come.
                try:
                    while True:
                        item = await loop.run_in_executor(None, _next)
                        if item is _SENTINEL:
                            break
                        kind, data = item
                        if kind == "accept":
                            continue  # upgrade already accepted above
                        if kind == "text":
                            await ws.send_str(data)
                        elif kind == "bytes":
                            await ws.send_bytes(data)
                        elif kind == "close":
                            await ws.close(code=data)
                            return
                    await ws.close()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    try:
                        await ws.close(code=1011)
                    except Exception:
                        pass

            pump = asyncio.create_task(_pump_out())
            # Frames carry proxy-assigned sequence numbers: ws_push
            # tasks execute on the replica's multi-threaded pool, so
            # arrival order is NOT delivery order — the replica
            # releases them to the app in seq order, and the final
            # disconnect takes the last seq so it can't overtake a
            # frame.
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    replica.handle_request.remote(
                        "ws_push", (conn_id, seq, "text", msg.data),
                        {}, "")
                    seq += 1
                elif msg.type == WSMsgType.BINARY:
                    replica.handle_request.remote(
                        "ws_push", (conn_id, seq, "bytes", msg.data),
                        {}, "")
                    seq += 1
                elif msg.type in (WSMsgType.CLOSE, WSMsgType.CLOSING,
                                  WSMsgType.ERROR):
                    break
            pump.cancel()
        except Exception:
            pass  # handshake/transport failure: cleanup below
        finally:
            if opened:
                try:
                    replica.handle_request.remote(
                        "ws_close", (conn_id, seq), {}, "")
                except Exception:
                    pass
            release()
        return ws

    def stop(self):
        self._state.stop()
        if self._runner is not None:
            async def _cleanup():
                await self._runner.cleanup()
            fut = asyncio.run_coroutine_threadsafe(_cleanup(), self._loop)
            try:
                fut.result(timeout=10)
            except Exception:
                pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)


_SENTINEL = object()


class ProxyReplica:
    """Actor wrapper hosting one HTTPProxy on ITS node — the controller
    schedules one per cluster node with hard NodeAffinity, giving every
    node a local ingress (reference: serve/_private/proxy_state.py
    ProxyStateManager — one proxy actor per node, reconciled by the
    controller; proxy.py:752)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        import ray_tpu
        from .controller import CONTROLLER_NAME
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        self._proxy = HTTPProxy(controller, host, port)
        self._node = ray_tpu.get_runtime_context().get_node_id()

    def address(self):
        """(node_id_hex, host, port) once the server is listening."""
        return (self._node, self._proxy.host, self._proxy.port)

    def check_health(self) -> bool:
        return self._thread_alive()

    def _thread_alive(self) -> bool:
        return self._proxy._thread.is_alive()
