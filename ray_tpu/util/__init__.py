from .actor_pool import ActorPool  # noqa: F401
from .queue import Queue  # noqa: F401

__all__ = ["ActorPool", "Queue"]
