from .actor_pool import ActorPool  # noqa: F401
from .placement_group import (  # noqa: F401
    PlacementGroup,
    get_current_placement_group,
    get_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from .queue import Queue  # noqa: F401

__all__ = [
    "ActorPool", "PlacementGroup", "Queue", "get_current_placement_group",
    "get_placement_group", "placement_group", "placement_group_table",
    "remove_placement_group",
]
