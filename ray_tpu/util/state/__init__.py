"""State observability API.

Reference parity: python/ray/util/state/api.py (`list_tasks`,
`list_actors`, `list_objects`, `list_nodes`, `list_placement_groups`,
`list_workers`, `summarize_*`) driven by the task-event store
(GcsTaskManager, gcs/gcs_server/gcs_task_manager.cc) — here the Node's
in-process event log (gcs.py record_task_event). `timeline()` exports
Chrome-trace JSON like `ray timeline` (_private/state.py).
"""
import json
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from ..._private import state as _state


def _gcs(op: str, **kwargs):
    rt = _state.current()
    return rt.gcs_request(op, **kwargs)


def _match(row: Dict[str, Any], filters) -> bool:
    for f in filters or []:
        key, op, value = f
        have = row.get(key)
        if op == "=" and not str(have) == str(value):
            return False
        if op == "!=" and str(have) == str(value):
            return False
    return True


_TERMINAL = ("FINISHED", "FAILED")


def list_tasks(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    """Latest state per task from the cluster-wide event aggregator
    (reference: state/api.py list_tasks over GcsTaskManager). Rows carry
    node/worker ids and the attempt number once events for them arrive
    (head-side SUBMITTED always has them; worker RUNNING/FINISHED land
    via the telemetry plane). A terminal event beats a non-terminal one
    regardless of source-clock ordering (worker and head clocks can
    disagree across hosts)."""
    events = _gcs("task_events")
    latest: Dict[str, Dict[str, Any]] = {}
    first_ts: Dict[str, float] = {}
    enrich: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        tid = ev["task_id"]
        first_ts.setdefault(tid, ev["ts"])
        e = enrich.setdefault(tid, {})
        for key in ("node_id", "worker_id", "attempt"):
            if ev.get(key) is not None:
                e[key] = ev[key]
        cur = latest.get(tid)
        if cur is None:
            latest[tid] = ev
            continue
        # Rank (terminal, attempt, ts): a later ATTEMPT beats an earlier
        # one even when both are terminal — attempts are stamped by the
        # head's ledger, so retried-then-succeeded tasks resolve
        # correctly regardless of cross-host clock skew; ts only breaks
        # ties within one attempt.
        if ((ev.get("state") in _TERMINAL, ev.get("attempt") or 0,
             ev["ts"])
                >= (cur.get("state") in _TERMINAL,
                    cur.get("attempt") or 0, cur["ts"])):
            latest[tid] = ev
    rows = []
    for tid, ev in latest.items():
        e = enrich.get(tid, {})
        row = {"task_id": tid, "name": ev.get("name"),
               "state": ev.get("state"),
               "worker_id": ev.get("worker_id") or e.get("worker_id"),
               "node_id": ev.get("node_id") or e.get("node_id"),
               "attempt": ev.get("attempt") or e.get("attempt"),
               "start_time": first_ts.get(tid), "end_time": ev["ts"]
               if ev.get("state") in _TERMINAL else None}
        if _match(row, filters):
            rows.append(row)
        if len(rows) >= limit:
            break
    return rows


def list_actors(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = [r for r in _gcs("list_actors") if _match(r, filters)]
    return rows[:limit]


def list_objects(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = [r for r in _gcs("list_objects", limit=limit)
            if _match(r, filters)]
    return rows[:limit]


def list_nodes(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    return [r for r in _gcs("list_nodes") if _match(r, filters)][:limit]


def list_workers(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    return [r for r in _gcs("list_workers") if _match(r, filters)][:limit]


def list_placement_groups(filters=None,
                          limit: int = 1000) -> List[Dict[str, Any]]:
    table = _gcs("pg_table")
    rows = []
    for pg_id, info in table.items():
        row = dict(info)
        row["placement_group_id"] = pg_id
        if _match(row, filters):
            rows.append(row)
    return rows[:limit]


# -- node drain (reference: the `ray drain-node` CLI / DrainNode RPC in
#    gcs_node_manager.cc; here the head's drain coordinator owns the
#    protocol — see docs/DRAIN.md) ---------------------------------------
def drain_node(node_id: str, deadline_s: Optional[float] = None,
               wait: bool = True) -> Dict[str, Any]:
    """Begin a graceful drain of `node_id`: stop new placement, let
    running tasks finish, migrate actors without charging restart
    budgets, re-home sole object copies, pull serve replicas out of
    routing. Returns the drain-status dict (state DRAINING / DRAINED /
    DEADLINE_EXCEEDED / NODE_DIED); with wait=True it reflects the
    final state."""
    return _gcs("drain_node", node_id=node_id, deadline_s=deadline_s,
                wait=wait)


def drain_status(node_id: Optional[str] = None):
    """Status dict for one drain, or all drains when node_id is None."""
    return _gcs("drain_status", node_id=node_id)


# -- summaries (reference: state/api.py summarize_*) ------------------------
def summarize_tasks() -> Dict[str, Dict[str, int]]:
    by_name: Dict[str, Dict[str, int]] = defaultdict(
        lambda: defaultdict(int))
    for row in list_tasks(limit=100000):
        by_name[row["name"] or "?"][row["state"]] += 1
    return {k: dict(v) for k, v in by_name.items()}


def summarize_actors() -> Dict[str, Dict[str, int]]:
    by_cls: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for row in list_actors(limit=100000):
        by_cls[row.get("class_name", "?")][row["state"]] += 1
    return {k: dict(v) for k, v in by_cls.items()}


def summarize_objects() -> Dict[str, int]:
    return _gcs("object_stats")


# -- timeline ---------------------------------------------------------------
def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace export of task execution spans across ALL nodes
    (reference: ray.timeline, _private/state.py — open in perfetto).
    Rows (pid) are nodes, threads (tid) are workers. Worker-reported
    terminal events carry same-clock ``start_ts`` bounds, so spans never
    mix two hosts' clocks; head-only events (telemetry disabled, or a
    worker that died mid-task) fall back to SUBMITTED/RUNNING ->
    terminal pairing on the head's clock."""
    events = _gcs("task_events")
    runs: Dict[str, Dict[str, Any]] = {}
    spanned = set()
    trace: List[Dict[str, Any]] = []

    def _emit(tid, start_ts, end_ev):
        trace.append({
            "name": end_ev.get("name") or tid[:8],
            "cat": "task", "ph": "X",
            "ts": start_ts * 1e6,
            "dur": max(0.0, (end_ev["ts"] - start_ts)) * 1e6,
            "pid": (end_ev.get("node_id") or "ray_tpu")[:8],
            "tid": (end_ev.get("worker_id") or "driver")[:8],
            "args": {"task_id": tid, "state": end_ev["state"],
                     "attempt": end_ev.get("attempt")},
        })

    for ev in events:
        tid = ev["task_id"]
        state = ev["state"]
        if state in ("RUNNING", "SUBMITTED"):
            cur = runs.get(tid)
            # RUNNING (worker-side actual start) refines SUBMITTED.
            if cur is None or state == "RUNNING":
                runs[tid] = ev
        elif state in _TERMINAL:
            if ev.get("start_ts") is not None:
                # Same-clock bounds straight from the worker.
                _emit(tid, ev["start_ts"], ev)
                spanned.add((tid, ev.get("attempt")))
                runs.pop(tid, None)
            elif tid in runs:
                if (tid, ev.get("attempt")) in spanned:
                    runs.pop(tid, None)
                    continue  # worker span already emitted for this try
                start = runs.pop(tid)
                merged = dict(ev)
                for key in ("node_id", "worker_id"):
                    if merged.get(key) is None:
                        merged[key] = start.get(key)
                _emit(tid, start["ts"], merged)
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


__all__ = ["drain_node", "drain_status", "list_actors", "list_nodes",
           "list_objects", "list_placement_groups", "list_tasks",
           "list_workers", "summarize_actors", "summarize_objects",
           "summarize_tasks", "timeline"]
