"""State observability API.

Reference parity: python/ray/util/state/api.py (`list_tasks`,
`list_actors`, `list_objects`, `list_nodes`, `list_placement_groups`,
`list_workers`, `summarize_*`) driven by the task-event store
(GcsTaskManager, gcs/gcs_server/gcs_task_manager.cc) — here the Node's
in-process event log (gcs.py record_task_event). `timeline()` exports
Chrome-trace JSON like `ray timeline` (_private/state.py).
"""
import json
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from ..._private import state as _state


def _gcs(op: str, **kwargs):
    rt = _state.current()
    return rt.gcs_request(op, **kwargs)


def _match(row: Dict[str, Any], filters) -> bool:
    for f in filters or []:
        key, op, value = f
        have = row.get(key)
        if op == "=" and not str(have) == str(value):
            return False
        if op == "!=" and str(have) == str(value):
            return False
    return True


def list_tasks(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    """Latest state per task (reference: state/api.py list_tasks)."""
    events = _gcs("task_events")
    latest: Dict[str, Dict[str, Any]] = {}
    first_ts: Dict[str, float] = {}
    for ev in events:
        tid = ev["task_id"]
        first_ts.setdefault(tid, ev["ts"])
        cur = latest.get(tid)
        if cur is None or ev["ts"] >= cur["ts"]:
            latest[tid] = ev
    rows = []
    for tid, ev in latest.items():
        row = {"task_id": tid, "name": ev.get("name"),
               "state": ev.get("state"),
               "worker_id": ev.get("worker_id"),
               "start_time": first_ts.get(tid), "end_time": ev["ts"]
               if ev.get("state") in ("FINISHED", "FAILED") else None}
        if _match(row, filters):
            rows.append(row)
        if len(rows) >= limit:
            break
    return rows


def list_actors(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = [r for r in _gcs("list_actors") if _match(r, filters)]
    return rows[:limit]


def list_objects(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    rows = [r for r in _gcs("list_objects", limit=limit)
            if _match(r, filters)]
    return rows[:limit]


def list_nodes(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    return [r for r in _gcs("list_nodes") if _match(r, filters)][:limit]


def list_workers(filters=None, limit: int = 1000) -> List[Dict[str, Any]]:
    return [r for r in _gcs("list_workers") if _match(r, filters)][:limit]


def list_placement_groups(filters=None,
                          limit: int = 1000) -> List[Dict[str, Any]]:
    table = _gcs("pg_table")
    rows = []
    for pg_id, info in table.items():
        row = dict(info)
        row["placement_group_id"] = pg_id
        if _match(row, filters):
            rows.append(row)
    return rows[:limit]


# -- summaries (reference: state/api.py summarize_*) ------------------------
def summarize_tasks() -> Dict[str, Dict[str, int]]:
    by_name: Dict[str, Dict[str, int]] = defaultdict(
        lambda: defaultdict(int))
    for row in list_tasks(limit=100000):
        by_name[row["name"] or "?"][row["state"]] += 1
    return {k: dict(v) for k, v in by_name.items()}


def summarize_actors() -> Dict[str, Dict[str, int]]:
    by_cls: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    for row in list_actors(limit=100000):
        by_cls[row.get("class_name", "?")][row["state"]] += 1
    return {k: dict(v) for k, v in by_cls.items()}


def summarize_objects() -> Dict[str, int]:
    return _gcs("object_stats")


# -- timeline ---------------------------------------------------------------
def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace export of task execution spans (reference:
    ray.timeline, _private/state.py — consumed at chrome://tracing)."""
    events = _gcs("task_events")
    runs: Dict[str, Dict[str, Any]] = {}
    trace: List[Dict[str, Any]] = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            runs[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in runs:
            start = runs.pop(tid)
            trace.append({
                "name": ev.get("name") or tid[:8],
                "cat": "task", "ph": "X",
                "ts": start["ts"] * 1e6,
                "dur": max(0.0, (ev["ts"] - start["ts"])) * 1e6,
                "pid": "ray_tpu",
                "tid": start.get("worker_id", "driver")[:8],
                "args": {"task_id": tid, "state": ev["state"]},
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


__all__ = ["list_actors", "list_nodes", "list_objects",
           "list_placement_groups", "list_tasks", "list_workers",
           "summarize_actors", "summarize_objects", "summarize_tasks",
           "timeline"]
