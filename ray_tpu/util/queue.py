"""Distributed Queue backed by an actor (reference:
python/ray/util/queue.py Queue/_QueueActor)."""

from __future__ import annotations

import time
from typing import Any, List, Optional

from .. import api


class Empty(Exception):
    pass


class Full(Exception):
    pass


@api.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        import collections
        self.maxsize = maxsize
        self.items = collections.deque()

    def qsize(self) -> int:
        return len(self.items)

    def empty(self) -> bool:
        return not self.items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self.items)

    def put(self, item) -> bool:
        if 0 < self.maxsize <= len(self.items):
            return False
        self.items.append(item)
        return True

    def put_batch(self, items) -> int:
        n = 0
        for item in items:
            if 0 < self.maxsize <= len(self.items):
                break
            self.items.append(item)
            n += 1
        return n

    def get(self):
        if not self.items:
            return (False, None)
        return (True, self.items.popleft())

    def get_batch(self, n: int):
        out = []
        while self.items and len(out) < n:
            out.append(self.items.popleft())
        return out


class Queue:
    """Same surface as the reference's util Queue; blocking semantics are
    implemented caller-side by polling the queue actor."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def qsize(self) -> int:
        return api.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return api.get(self.actor.empty.remote())

    def full(self) -> bool:
        return api.get(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if api.get(self.actor.put.remote(item)):
                return
            if not block:
                raise Full()
            if deadline is not None and time.monotonic() > deadline:
                raise Full()
            time.sleep(0.01)

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]):
        n = api.get(self.actor.put_batch.remote(items))
        if n != len(items):
            raise Full(f"only {n}/{len(items)} items fit")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = api.get(self.actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty()
            if deadline is not None and time.monotonic() > deadline:
                raise Empty()
            time.sleep(0.01)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return api.get(self.actor.get_batch.remote(num_items))

    def shutdown(self, force: bool = False):
        api.kill(self.actor)
