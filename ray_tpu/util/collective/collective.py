"""Public collective API (reference:
python/ray/util/collective/collective.py, 789 lines — the full surface:
init_collective_group :120, create_collective_group :151, allreduce :258,
barrier :298, reduce :311, broadcast :373, allgather :423, reducescatter
:472, send :531, recv :594).

Functional style difference from the reference: the reference mutates torch
tensors in place (NCCL semantics); jax arrays are immutable, so every op
*returns* the result. `allreduce(t)` -> reduced array on every rank.

Usage inside actors (one rank per actor process):

    import ray_tpu
    from ray_tpu.util import collective as col

    @ray_tpu.remote
    class Worker:
        def setup(self, world_size, rank):
            col.init_collective_group(world_size, rank, "xla", "default")

        def step(self, grad):
            return col.allreduce(grad, "default")

For code already inside a jit/shard_map (the ICI hot path), use
`ray_tpu.parallel.ops` (lax.psum et al.) — this module is the eager,
actor-to-actor surface.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

import numpy as np

from .types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    Backend,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)

logger = logging.getLogger(__name__)


class GroupManager:
    """Per-process registry of collective groups (reference:
    collective.py:40 GroupManager)."""

    def __init__(self):
        self._groups: Dict[str, object] = {}
        self._lock = threading.Lock()

    def create_group(self, backend: str, world_size: int, rank: int,
                     group_name: str):
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(
                    f"Group '{group_name}' already initialized in this "
                    f"process.")
            if world_size == 1 or backend == "local":
                from .collective_group.local_group import LocalGroup
                g = LocalGroup(world_size, rank, group_name)
            else:
                from .collective_group.xla_collective_group import XLAGroup
                g = XLAGroup(world_size, rank, group_name)
            self._groups[group_name] = g
            return g

    def get_group(self, group_name: str):
        with self._lock:
            return self._groups.get(group_name)

    def destroy_group(self, group_name: str):
        with self._lock:
            g = self._groups.pop(group_name, None)
        if g is not None:
            g.destroy_group()


_group_mgr = GroupManager()


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.get_group(group_name) is not None


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default"):
    """Imperative group init, called inside each member actor/task
    (reference: collective.py:120)."""
    if not isinstance(world_size, int) or world_size < 1:
        raise ValueError(f"world_size must be a positive int, "
                         f"got {world_size}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range [0, {world_size})")
    backend = Backend(backend)
    return _group_mgr.create_group(backend, world_size, rank, group_name)


def create_collective_group(actors: List, world_size: int,
                            ranks: List[int], backend: str = "xla",
                            group_name: str = "default"):
    """Declarative group creation from the driver (reference:
    collective.py:151): records membership in the GCS KV; each member must
    still call `init_collective_group` (or have it called via a method) to
    join its rank. Returns after metadata is stored."""
    if len(actors) != len(ranks) or sorted(ranks) != list(range(world_size)):
        raise ValueError("ranks must be a permutation of range(world_size) "
                         "matching `actors`")
    from ..._private import serialization, state
    info = {"world_size": world_size, "backend": Backend(backend),
            "ranks": {a._id.hex(): r for a, r in zip(actors, ranks)}}
    state.current().gcs_request(
        "kv_put", key=f"{group_name}/decl",
        value=serialization.dumps(info), namespace="collective")
    return info


def get_group_info(group_name: str = "default") -> Optional[dict]:
    from ..._private import serialization, state
    raw = state.current().gcs_request(
        "kv_get", key=f"{group_name}/decl", namespace="collective")
    return serialization.loads(raw) if raw else None


def destroy_collective_group(group_name: str = "default"):
    _group_mgr.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    g = _group_mgr.get_group(group_name)
    return g.rank if g is not None else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _group_mgr.get_group(group_name)
    return g.world_size if g is not None else -1


def _group(group_name: str):
    g = _group_mgr.get_group(group_name)
    if g is None:
        raise RuntimeError(
            f"Collective group '{group_name}' is not initialized in this "
            f"process; call init_collective_group first.")
    return g


# -- ops (all return the result; see module docstring) ----------------------
def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    return _group(group_name).allreduce(
        tensor, AllReduceOptions(reduceOp=op))


def barrier(group_name: str = "default"):
    _group(group_name).barrier(BarrierOptions())


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    return _group(group_name).reduce(
        tensor, ReduceOptions(reduceOp=op, root_rank=dst_rank))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group(group_name).broadcast(
        tensor, BroadcastOptions(src_rank=src_rank))


def allgather(tensor, group_name: str = "default"):
    return _group(group_name).allgather(tensor, AllGatherOptions())


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    return _group(group_name).reducescatter(
        tensor, ReduceScatterOptions(reduceOp=op))


def send(tensor, dst_rank: int, group_name: str = "default"):
    """P2P send (reference collective.py:531). With the xla backend this is
    a gang op — every rank of the group must call send or recv."""
    return _group(group_name).send(tensor, SendOptions(dst_rank=dst_rank))


def recv(shape_or_tensor, src_rank: int, group_name: str = "default"):
    return _group(group_name).recv(
        shape_or_tensor, RecvOptions(src_rank=src_rank))


# torch-API-style aliases kept for reference-parity call sites
def allreduce_multigpu(tensor_list, group_name: str = "default",
                       op: ReduceOp = ReduceOp.SUM):
    return [allreduce(t, group_name, op) for t in tensor_list]
