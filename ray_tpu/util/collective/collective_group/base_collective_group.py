"""Abstract collective group (reference:
python/ray/util/collective/collective_group/base_collective_group.py)."""

from __future__ import annotations

from abc import ABC, abstractmethod

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    def destroy_group(self):
        pass

    @classmethod
    @abstractmethod
    def backend(cls) -> str:
        ...

    @abstractmethod
    def allreduce(self, tensors, opts: AllReduceOptions = AllReduceOptions()):
        ...

    @abstractmethod
    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        ...

    @abstractmethod
    def reduce(self, tensors, opts: ReduceOptions = ReduceOptions()):
        ...

    @abstractmethod
    def allgather(self, tensors,
                  opts: AllGatherOptions = AllGatherOptions()):
        ...

    @abstractmethod
    def broadcast(self, tensors,
                  opts: BroadcastOptions = BroadcastOptions()):
        ...

    @abstractmethod
    def reducescatter(self, tensors,
                      opts: ReduceScatterOptions = ReduceScatterOptions()):
        ...

    @abstractmethod
    def send(self, tensors, opts: SendOptions):
        ...

    @abstractmethod
    def recv(self, tensors, opts: RecvOptions):
        ...
