"""XLA-backed collective group: TPU ICI/DCN device collectives.

TPU-native replacement for the reference's NCCL group
(python/ray/util/collective/collective_group/nccl_collective_group.py:128
NCCLGroup) and its GLOO CPU mirror: one rank per worker process, rendezvous
through the GCS KV store (replacing the named-actor `Rendezvous` holding an
NCCLUniqueID, nccl_collective_group.py:29-124), and a `jax.distributed`
runtime + device mesh replacing cupy-NCCL communicators.

Every op builds a global jax.Array whose leading axis is sharded across the
group's processes and runs a tiny jitted program whose output sharding forces
XLA to insert the collective (all-reduce, all-gather, reduce-scatter) — so on
TPU the bytes ride ICI, and on CPU the same code path rides the
jax.distributed gRPC transport. This is the "same test matrix against a
host-CPU jax backend vs real ICI" pattern from SURVEY.md §4.

Constraint: `jax.distributed.initialize` is once-per-process, so all groups
in one process must span the same process set (the reference's NCCL comms
have an analogous one-comm-per-device-set restriction).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)
from .base_collective_group import BaseGroup

_KV_NS = "collective"
_init_lock = threading.Lock()
_distributed_state: Dict[str, object] = {}


def _kv():
    from ...._private import state
    return state.current()


def _kv_put(key: str, value: bytes):
    _kv().gcs_request("kv_put", key=key, value=value, namespace=_KV_NS)


def _kv_get(key: str) -> Optional[bytes]:
    return _kv().gcs_request("kv_get", key=key, namespace=_KV_NS)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rendezvous(group_name: str, world_size: int, rank: int,
                timeout_s: float = 60.0) -> str:
    """Agree on a jax.distributed coordinator address via the GCS KV
    (reference: Rendezvous via named actor, nccl_collective_group.py:29)."""
    key = f"{group_name}/coordinator"
    if rank == 0:
        addr = f"127.0.0.1:{_free_port()}"
        _kv_put(key, addr.encode())
        return addr
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        raw = _kv_get(key)
        if raw:
            return raw.decode()
        time.sleep(0.05)
    raise TimeoutError(
        f"Rendezvous for group '{group_name}' timed out after {timeout_s}s")


def ensure_distributed(coordinator: str, world_size: int, rank: int):
    """Initialize the jax.distributed runtime exactly once per process
    (replaces dist.init_process_group / NCCL comm init)."""
    with _init_lock:
        if _distributed_state:
            prev = _distributed_state
            if (prev["world_size"] != world_size or prev["rank"] != rank):
                raise RuntimeError(
                    "jax.distributed already initialized with a different "
                    f"topology ({prev}); one process set per process.")
            return
        import jax
        if world_size > 1:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank)
        _distributed_state.update(
            {"world_size": world_size, "rank": rank,
             "coordinator": coordinator})


class XLAGroup(BaseGroup):
    """One collective group == one 1-D 'world' device mesh."""

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        coordinator = _rendezvous(group_name, world_size, rank)
        ensure_distributed(coordinator, world_size, rank)
        import jax
        self._jax = jax
        # One representative device per process => 'world' axis length equals
        # the number of ranks regardless of chips-per-host.
        per_proc: Dict[int, object] = {}
        for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
            per_proc.setdefault(d.process_index, d)
        if len(per_proc) != world_size:
            raise RuntimeError(
                f"Group '{group_name}': expected {world_size} processes, "
                f"found {len(per_proc)} in the jax runtime.")
        from jax.sharding import Mesh
        self._devices = [per_proc[i] for i in sorted(per_proc)]
        self._mesh = Mesh(np.array(self._devices), ("world",))
        self._local_device = per_proc[jax.process_index()]
        self._jit_cache: Dict[Tuple, object] = {}

    @classmethod
    def backend(cls) -> str:
        return "xla"

    # -- plumbing ----------------------------------------------------------
    def _sharding(self, spec):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self._mesh, PartitionSpec(*spec))

    def _global_from_local(self, tensor):
        """Stack per-rank tensors into a (world, *shape) global array whose
        leading axis is sharded one-slice-per-process."""
        import jax
        import jax.numpy as jnp
        x = jnp.asarray(tensor)
        local = jax.device_put(x[None], self._local_device)
        return jax.make_array_from_single_device_arrays(
            (self._world_size,) + x.shape,
            self._sharding(("world",)),
            [local])

    def _jit(self, key, builder):
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = builder()
            self._jit_cache[key] = fn
        return fn

    def _read_replicated(self, garr) -> np.ndarray:
        return np.asarray(garr.addressable_shards[0].data)

    @staticmethod
    def _reduce_fn(op: ReduceOp):
        import jax.numpy as jnp
        return {ReduceOp.SUM: jnp.sum, ReduceOp.PRODUCT: jnp.prod,
                ReduceOp.MIN: jnp.min, ReduceOp.MAX: jnp.max}[op]

    # -- collectives -------------------------------------------------------
    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        """All ranks get reduce(tensor over ranks). XLA lowers the sharded
        reduction to an AllReduce over ICI (the jit path's lax.psum
        equivalent, reference API collective.py:258)."""
        import jax
        garr = self._global_from_local(tensor)
        red = self._reduce_fn(opts.reduceOp)
        key = ("allreduce", opts.reduceOp, garr.shape, str(garr.dtype))
        fn = self._jit(key, lambda: jax.jit(
            lambda g: red(g, axis=0),
            out_shardings=self._sharding(())))
        return self._read_replicated(fn(garr))

    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        """Returns the stacked (world, *shape) array on every rank
        (reference API collective.py:423)."""
        import jax
        garr = self._global_from_local(tensor)
        key = ("allgather", garr.shape, str(garr.dtype))
        fn = self._jit(key, lambda: jax.jit(
            lambda g: g, out_shardings=self._sharding(())))
        return self._read_replicated(fn(garr))

    def reducescatter(self, tensor,
                      opts: ReduceScatterOptions = ReduceScatterOptions()):
        """Each rank gets its 1/world chunk of the reduced tensor
        (reference API collective.py:472). Requires dim0 % world == 0."""
        import jax
        if tensor.shape[0] % self._world_size != 0:
            raise ValueError(
                f"reducescatter needs dim0 divisible by world size "
                f"({tensor.shape[0]} % {self._world_size})")
        garr = self._global_from_local(tensor)
        red = self._reduce_fn(opts.reduceOp)
        key = ("reducescatter", opts.reduceOp, garr.shape, str(garr.dtype))
        fn = self._jit(key, lambda: jax.jit(
            lambda g: red(g, axis=0),
            out_shardings=self._sharding(("world",))))
        out = fn(garr)
        return np.asarray(out.addressable_shards[0].data)

    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        """Reduce to root (reference collective.py:311); other ranks get
        the reduced value too (XLA all-reduce; harmless superset)."""
        return self.allreduce(
            tensor, AllReduceOptions(reduceOp=opts.reduceOp))

    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        """src_rank's tensor to all (reference collective.py:373)."""
        import jax.numpy as jnp
        x = jnp.asarray(tensor)
        mask = 1.0 if self._rank == opts.src_rank else 0.0
        contrib = np.asarray(x) * mask
        return self.allreduce(contrib)

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        self.allreduce(np.zeros((1,), dtype=np.float32))

    def send(self, tensor, opts: SendOptions):
        """P2P send (reference collective.py:531). Implemented as a gang op:
        all ranks enter, dst reads the gathered slice — correct though not
        minimal-traffic; a ppermute fast path lands with the pipeline layer."""
        self.allgather(np.asarray(tensor))
        return None

    def recv(self, shape_dtype_or_tensor, opts: RecvOptions):
        import numpy as _np
        if isinstance(shape_dtype_or_tensor, tuple):
            shape, dtype = shape_dtype_or_tensor
            template = _np.zeros(shape, dtype=dtype)
        else:
            template = _np.asarray(shape_dtype_or_tensor)
        gathered = self.allgather(template)
        return gathered[opts.src_rank]

    def destroy_group(self):
        self._jit_cache.clear()
