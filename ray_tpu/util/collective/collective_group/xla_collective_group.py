"""XLA-backed collective group: TPU ICI/DCN device collectives.

TPU-native replacement for the reference's NCCL group
(python/ray/util/collective/collective_group/nccl_collective_group.py:128
NCCLGroup) and its GLOO CPU mirror: one rank per worker process, rendezvous
through the GCS KV store (replacing the named-actor `Rendezvous` holding an
NCCLUniqueID, nccl_collective_group.py:29-124), and a `jax.distributed`
runtime + device mesh replacing cupy-NCCL communicators.

Every op builds a global jax.Array whose leading axis is sharded across the
group's processes and runs a tiny jitted program whose output sharding forces
XLA to insert the collective (all-reduce, all-gather, reduce-scatter) — so on
TPU the bytes ride ICI, and on CPU the same code path rides the
jax.distributed gRPC transport. This is the "same test matrix against a
host-CPU jax backend vs real ICI" pattern from SURVEY.md §4.

Subset groups (reference: GroupManager supporting multiple groups with
different member sets per process, collective.py:40,120): the FIRST group
a process joins initializes the one-per-process `jax.distributed`
runtime; any later group whose topology differs is treated as a SUBSET
over that global runtime — members publish their global process index
through the KV, and the group's mesh is built from just those
processes' devices. Ops over a subset mesh are programs only the member
processes enter (the same pairwise-mesh trick the p2p path uses), so
e.g. disjoint TP groups inside a DP world each allreduce independently.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)
from .base_collective_group import BaseGroup

_KV_NS = "collective"
_init_lock = threading.Lock()
_distributed_state: Dict[str, object] = {}


def _kv():
    from ...._private import state
    return state.current()


def _kv_put(key: str, value: bytes):
    _kv().gcs_request("kv_put", key=key, value=value, namespace=_KV_NS)


def _kv_get(key: str) -> Optional[bytes]:
    return _kv().gcs_request("kv_get", key=key, namespace=_KV_NS)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rendezvous(group_name: str, world_size: int, rank: int,
                timeout_s: float = 60.0, gen: str = "") -> str:
    """Agree on a jax.distributed coordinator address via the GCS KV
    (reference: Rendezvous via named actor, nccl_collective_group.py:29).
    Keys are namespaced per group GENERATION (`gen`, rotated by rank 0
    on every creation attempt) so a coordinator address left by a
    crashed prior group of the same name can never be handed to a new
    one."""
    key = f"{group_name}/{gen}/coordinator"
    if rank == 0:
        addr = f"127.0.0.1:{_free_port()}"
        _kv_put(key, addr.encode())
        return addr
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        raw = _kv_get(key)
        if raw:
            return raw.decode()
        time.sleep(0.05)
    raise TimeoutError(
        f"Rendezvous for group '{group_name}' timed out after {timeout_s}s")


def ensure_distributed(coordinator: str, world_size: int, rank: int,
                       strict: bool = True):
    """Initialize the jax.distributed runtime exactly once per process
    (replaces dist.init_process_group / NCCL comm init). With
    ``strict`` (the default — train's JaxBackendConfig.on_start), an
    already-initialized runtime with a DIFFERENT topology raises
    loudly: silently keeping the stale topology would make later
    collectives hang or run with wrong world semantics. Group creation
    (XLAGroup) passes strict=False because its membership comes from
    the KV rendezvous, not the runtime topology."""
    with _init_lock:
        if _distributed_state:
            prev = _distributed_state
            if strict and (prev["world_size"] != world_size
                           or prev["rank"] != rank):
                raise RuntimeError(
                    "jax.distributed already initialized with a "
                    f"different topology ({prev}); a worker process "
                    "cannot re-initialize at a new world size — "
                    "elastic resizes must restart worker processes.")
            return
        import jax
        if world_size > 1:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank)
        import os as _os
        _distributed_state.update(
            {"world_size": world_size, "rank": rank,
             # A world_size-1 "runtime" has no shared coordinator; tag
             # it per-process so split-brain detection never mistakes
             # two solo runtimes for a shared one.
             "coordinator": coordinator or f"local:{_os.getpid()}"})


class XLAGroup(BaseGroup):
    """One collective group == one 1-D 'world' device mesh."""

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        # Phase A: agree on runtime state across ALL members before any
        # blocking jax.distributed.initialize — a per-process decision
        # here deadlocks groups that mix initialized and uninitialized
        # processes (one side waits in initialize, the other skips it),
        # and silently accepts split-brain groups spanning two separate
        # runtimes. Members publish their state; creation proceeds only
        # when all are fresh (one shared initialize) or all already
        # share ONE runtime (subset group).
        mode, coordinator, gen = self._pre_rendezvous(group_name,
                                                      world_size, rank)
        self._gen = gen
        if mode == "create":
            ensure_distributed(coordinator, world_size, rank,
                               strict=False)
        import jax
        self._jax = jax
        # One representative device per process => 'world' axis length equals
        # the number of ranks regardless of chips-per-host.
        per_proc: Dict[int, object] = {}
        for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
            per_proc.setdefault(d.process_index, d)
        # EVERY group resolves membership through the KV — including a
        # whole-world group. Deciding owner-vs-subset per process from
        # local runtime state is unsound (two members of one group could
        # take different paths and deadlock); uniform KV resolution is
        # one put + world_size gets, trivial next to the jax init.
        member_procs = self._subset_members(group_name, world_size,
                                            rank, jax.process_index(),
                                            gen=gen)
        if len(set(member_procs)) != world_size:
            raise RuntimeError(
                f"Group '{group_name}': member process indices "
                f"{member_procs} are not distinct — the members do not "
                "share one jax.distributed runtime (a process that "
                "first created a world_size=1 group never joins a "
                "shared runtime; create the multi-process group first).")
        for p in member_procs:
            if p not in per_proc:
                raise RuntimeError(
                    f"Group '{group_name}': member process {p} has no "
                    "devices in the jax runtime.")
        from jax.sharding import Mesh
        self._devices = [per_proc[p] for p in member_procs]
        self._mesh = Mesh(np.array(self._devices), ("world",))
        self._local_device = per_proc[jax.process_index()]
        self._jit_cache: Dict[Tuple, object] = {}

    @staticmethod
    def _generation(group_name: str, rank: int, deadline: float) -> str:
        """Resolve this creation attempt's generation nonce. Rank 0
        ROTATES it (fresh uuid per attempt); other ranks poll for it —
        and keep following it if it changes (a stale nonce from a
        crashed prior group is superseded the moment the live rank 0
        publishes). Namespacing all rendezvous keys under the nonce
        makes a dead group's leftovers invisible instead of spuriously
        failing (or worse, spuriously satisfying) a valid new group."""
        key = f"{group_name}/gen"
        if rank == 0:
            import uuid
            stale = _kv_get(key)
            gen = uuid.uuid4().hex[:8]
            _kv_put(key, gen.encode())
            if stale:
                # A prior generation that was never destroyed (crashed
                # group): burn its keys so no member can complete a
                # rendezvous against the dead state.
                sg = stale.decode()
                for k in ([f"{group_name}/{sg}/coordinator"]
                          + [f"{group_name}/{sg}/{kind}/{r}"
                             for kind in ("pre", "proc", "confirm")
                             for r in range(64)]):
                    try:
                        _kv().gcs_request("kv_del", key=k,
                                          namespace=_KV_NS)
                    except Exception:
                        break
            return gen
        while time.monotonic() < deadline:
            raw = _kv_get(key)
            if raw is not None:
                gen = raw.decode()
                # Own-key discriminator: under a CURRENT generation this
                # rank's pre key cannot exist before this rank publishes
                # it — its presence proves `gen` is a crashed prior
                # group's leftover pointer read before the live rank 0
                # rotated it. Keep polling for the rotation instead of
                # completing a rendezvous against dead state (and
                # possibly adopting its dead coordinator).
                if _kv_get(f"{group_name}/{gen}/pre/{rank}") is None:
                    return gen
            time.sleep(0.05)
        raise TimeoutError(
            f"group '{group_name}' rendezvous: no fresh generation "
            f"published (rank 0 absent or only stale state found)")

    @staticmethod
    def _pre_rendezvous(group_name: str, world_size: int, rank: int,
                        timeout_s: float = 60.0):
        """Pre-init agreement: every member publishes whether its
        process already runs a jax.distributed runtime (and which, by
        coordinator tag). Returns ("create", coordinator, gen) when all
        members are fresh, ("join", tag, gen) when all share one
        runtime; raises for mixed membership or two different runtimes —
        those groups cannot work (a process cannot join a runtime late),
        so fail loudly instead of hanging in initialize/collectives.

        All keys live under the per-attempt generation nonce (see
        _generation), so keys from a crashed earlier group of the same
        name cannot leak into this agreement."""
        from ...._private import fault
        if fault.enabled:
            fault.fire("collective.rendezvous", group=group_name,
                       rank=rank)
        with _init_lock:
            my_tag = (_distributed_state.get("coordinator")
                      if _distributed_state else "uninit")
        deadline = time.monotonic() + timeout_s
        gen = XLAGroup._generation(group_name, rank, deadline)
        _kv_put(f"{group_name}/{gen}/pre/{rank}", str(my_tag).encode())
        last_tags = None
        mixed_since = None
        # Mixed-state grace scales with the caller's budget: members of
        # big clusters legitimately straggle past a fixed 3s (cold jax
        # import), and short-timeout callers shouldn't wait 3s to fail.
        grace = min(max(3.0, 0.25 * timeout_s), 0.5 * timeout_s)
        while time.monotonic() < deadline:
            if rank != 0:
                cur = _kv_get(f"{group_name}/gen")
                cur_gen = cur.decode() if cur else gen
                if cur_gen != gen:
                    # Rank 0 started a newer attempt: follow it.
                    gen = cur_gen
                    _kv_put(f"{group_name}/{gen}/pre/{rank}",
                            str(my_tag).encode())
                    mixed_since = None
            tags = []
            for r in range(world_size):
                raw = _kv_get(f"{group_name}/{gen}/pre/{r}")
                tags.append(raw.decode() if raw is not None else None)
            if None not in tags:
                last_tags = tags
                if all(t == "uninit" for t in tags):
                    remaining = max(1.0, deadline - time.monotonic())
                    return ("create",
                            _rendezvous(group_name, world_size, rank,
                                        timeout_s=remaining, gen=gen),
                            gen)
                if "uninit" not in tags and len(set(tags)) == 1:
                    return ("join", tags[0], gen)
                # Mixed / divergent live members: give stragglers the
                # scaled grace window to overwrite before failing.
                now = time.monotonic()
                mixed_since = mixed_since or now
                if now - mixed_since >= grace:
                    raise RuntimeError(
                        f"Group '{group_name}': members span "
                        f"incompatible runtime states {tags} — every "
                        "member must either be fresh (first group "
                        "creates the runtime) or already share ONE "
                        "jax.distributed runtime; a process cannot "
                        "join an existing runtime late.")
            else:
                mixed_since = None
            time.sleep(0.1)
        raise TimeoutError(
            f"group '{group_name}' pre-rendezvous timed out "
            f"(tags={last_tags})")

    @staticmethod
    def _subset_members(group_name: str, world_size: int, rank: int,
                        my_process_index: int,
                        timeout_s: float = 60.0, gen: str = "") -> list:
        """Publish this member's global process index; wait for all
        world_size members, returning their process indices in
        group-rank order (rank i of the group == i-th entry).

        Keys are namespaced under the group generation (gen) resolved
        by _pre_rendezvous, so keys from a crashed earlier group of the
        same name are invisible here. The confirm round still guards
        against divergent first reads WITHIN a generation: every member
        publishes the membership signature it resolved and loops until
        all members published the SAME signature."""
        _kv_put(f"{group_name}/{gen}/proc/{rank}",
                str(my_process_index).encode())
        deadline = time.monotonic() + timeout_s

        def _poll(key):
            while time.monotonic() < deadline:
                raw = _kv_get(key)
                if raw is not None:
                    return raw
                time.sleep(0.05)
            raise TimeoutError(
                f"group '{group_name}' rendezvous timed out on {key}")

        while True:
            members = [int(_poll(f"{group_name}/{gen}/proc/{r}").decode())
                       for r in range(world_size)]
            sig = ",".join(map(str, members))
            _kv_put(f"{group_name}/{gen}/confirm/{rank}", sig.encode())
            agreed = True
            for r in range(world_size):
                other = _poll(f"{group_name}/{gen}/confirm/{r}").decode()
                if other != sig:
                    agreed = False
                    break
            if agreed:
                return members
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"group '{group_name}' members disagree on "
                    f"membership ({sig} vs {other})")
            time.sleep(0.1)

    @classmethod
    def backend(cls) -> str:
        return "xla"

    # -- plumbing ----------------------------------------------------------
    def _sharding(self, spec):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self._mesh, PartitionSpec(*spec))

    def _global_from_local(self, tensor):
        """Stack per-rank tensors into a (world, *shape) global array whose
        leading axis is sharded one-slice-per-process."""
        import jax
        import jax.numpy as jnp
        x = jnp.asarray(tensor)
        local = jax.device_put(x[None], self._local_device)
        return jax.make_array_from_single_device_arrays(
            (self._world_size,) + x.shape,
            self._sharding(("world",)),
            [local])

    def _jit(self, key, builder):
        fn = self._jit_cache.get(key)
        if fn is None:
            fn = builder()
            self._jit_cache[key] = fn
        return fn

    def _read_replicated(self, garr) -> np.ndarray:
        return np.asarray(garr.addressable_shards[0].data)

    @staticmethod
    def _reduce_fn(op: ReduceOp):
        import jax.numpy as jnp
        return {ReduceOp.SUM: jnp.sum, ReduceOp.PRODUCT: jnp.prod,
                ReduceOp.MIN: jnp.min, ReduceOp.MAX: jnp.max}[op]

    # -- collectives -------------------------------------------------------
    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        """All ranks get reduce(tensor over ranks). XLA lowers the sharded
        reduction to an AllReduce over ICI (the jit path's lax.psum
        equivalent, reference API collective.py:258)."""
        import jax
        garr = self._global_from_local(tensor)
        red = self._reduce_fn(opts.reduceOp)
        key = ("allreduce", opts.reduceOp, garr.shape, str(garr.dtype))
        fn = self._jit(key, lambda: jax.jit(
            lambda g: red(g, axis=0),
            out_shardings=self._sharding(())))
        return self._read_replicated(fn(garr))

    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        """Returns the stacked (world, *shape) array on every rank
        (reference API collective.py:423)."""
        import jax
        garr = self._global_from_local(tensor)
        key = ("allgather", garr.shape, str(garr.dtype))
        fn = self._jit(key, lambda: jax.jit(
            lambda g: g, out_shardings=self._sharding(())))
        return self._read_replicated(fn(garr))

    def reducescatter(self, tensor,
                      opts: ReduceScatterOptions = ReduceScatterOptions()):
        """Each rank gets its 1/world chunk of the reduced tensor
        (reference API collective.py:472). Requires dim0 % world == 0."""
        import jax
        if tensor.shape[0] % self._world_size != 0:
            raise ValueError(
                f"reducescatter needs dim0 divisible by world size "
                f"({tensor.shape[0]} % {self._world_size})")
        garr = self._global_from_local(tensor)
        red = self._reduce_fn(opts.reduceOp)
        key = ("reducescatter", opts.reduceOp, garr.shape, str(garr.dtype))
        fn = self._jit(key, lambda: jax.jit(
            lambda g: red(g, axis=0),
            out_shardings=self._sharding(("world",))))
        out = fn(garr)
        return np.asarray(out.addressable_shards[0].data)

    @staticmethod
    def _tree_steps(n: int):
        steps = []
        step = 1
        while step < n:
            steps.append(step)
            step *= 2
        return steps

    def _shard_map_op(self, key, body):
        """jit(shard_map(body)) over the world mesh, P('world')->P('world')."""
        import jax
        from ray_tpu.parallel.ops import shard_map
        from jax.sharding import PartitionSpec as P

        def build():
            fn = shard_map(body, mesh=self._mesh,
                           in_specs=P("world"), out_specs=P("world"))
            return jax.jit(fn)

        return self._jit(key, build)

    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        """Reduce to root (reference collective.py:311): binomial
        tree-fold via ``ppermute`` — each round halves the holders,
        payloads flow TOWARD root, every byte crosses each link once
        (O(bytes) per link, log2(world) rounds; HLO: collective-permutes
        only, no all-reduce — verified in tests). Root returns the
        reduced tensor; other ranks return their input unchanged."""
        import jax.numpy as jnp
        from jax import lax

        if self._world_size == 1:
            return np.asarray(tensor)
        n = self._world_size
        root = opts.root_rank
        op = opts.reduceOp
        combine = {ReduceOp.SUM: jnp.add, ReduceOp.PRODUCT: jnp.multiply,
                   ReduceOp.MIN: jnp.minimum, ReduceOp.MAX: jnp.maximum}[op]

        def body(t):
            my_dist = (lax.axis_index("world") - root) % n
            for step in reversed(self._tree_steps(n)):
                perm = [((root + d) % n, (root + d - step) % n)
                        for d in range(step, min(2 * step, n))]
                recv = lax.ppermute(t, "world", perm)
                use = jnp.logical_and(my_dist < step, my_dist + step < n)
                t = jnp.where(use, combine(t, recv), t)
            return t

        garr = self._global_from_local(tensor)
        key = ("reduce", op, root, garr.shape, str(garr.dtype))
        out = self._shard_map_op(key, body)(garr)
        if self._rank == root:
            return np.asarray(out.addressable_shards[0].data)[0]
        return np.asarray(tensor)

    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        """src_rank's tensor to all (reference collective.py:373):
        binomial-tree broadcast via ``ppermute`` — holders double each
        round, each receiving rank's payload crosses its link exactly
        once (log2(world) rounds; HLO: collective-permutes only, no
        all-reduce — the round-1 masked-allreduce paid reduce+broadcast).
        """
        import jax.numpy as jnp
        from jax import lax

        if self._world_size == 1:
            return np.asarray(tensor)
        n = self._world_size
        src = opts.src_rank

        def body(t):
            my_dist = (lax.axis_index("world") - src) % n
            for step in self._tree_steps(n):
                perm = [((src + i) % n, (src + i + step) % n)
                        for i in range(step) if i + step < n]
                recv = lax.ppermute(t, "world", perm)
                use = jnp.logical_and(my_dist >= step, my_dist < 2 * step)
                t = jnp.where(use, recv, t)
            return t

        garr = self._global_from_local(tensor)
        key = ("broadcast", src, garr.shape, str(garr.dtype))
        out = self._shard_map_op(key, body)(garr)
        return np.asarray(out.addressable_shards[0].data)[0]

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        self.allreduce(np.zeros((1,), dtype=np.float32))

    # -- p2p ---------------------------------------------------------------
    def _p2p(self, x: np.ndarray, src: int, dst: int):
        """Point-to-point transfer via ``lax.ppermute`` over a two-device
        mesh: only src and dst enter the program, and the traffic is ONE
        payload over one link (O(bytes) — replaces the round-1
        gang-allgather placeholder, which moved world*bytes). Reference:
        the NCCL send/recv pair (nccl_collective_group.py p2p)."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ray_tpu.parallel.ops import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        if src == dst:
            return np.asarray(x)
        xj = jnp.asarray(x)
        src_dev = self._devices[src]
        dst_dev = self._devices[dst]
        pair = Mesh(np.array([src_dev, dst_dev]), ("pair",))
        sharding = NamedSharding(pair, P("pair"))
        local = jax.device_put(
            xj[None] if self._rank == src else jnp.zeros_like(xj)[None],
            src_dev if self._rank == src else dst_dev)
        garr = jax.make_array_from_single_device_arrays(
            (2,) + xj.shape, sharding, [local])
        key = ("p2p", src, dst, xj.shape, str(xj.dtype))

        def build():
            fn = shard_map(
                lambda t: lax.ppermute(t, "pair", [(0, 1)]),
                mesh=pair, in_specs=P("pair"), out_specs=P("pair"))
            return jax.jit(fn)

        out = self._jit(key, build)(garr)
        if self._rank == dst:
            # Local shard is (1, *shape) — the pair-axis block.
            return np.asarray(out.addressable_shards[0].data)[0]
        return None

    def send(self, tensor, opts: SendOptions):
        """P2P send (reference collective.py:531). Only src and dst enter
        (pairwise program); traffic is one payload over one link."""
        self._p2p(np.asarray(tensor), self._rank, opts.dst_rank)
        return None

    def recv(self, shape_dtype_or_tensor, opts: RecvOptions):
        import numpy as _np
        if isinstance(shape_dtype_or_tensor, tuple):
            shape, dtype = shape_dtype_or_tensor
            template = _np.zeros(shape, dtype=dtype)
        else:
            template = _np.asarray(shape_dtype_or_tensor)
        return self._p2p(template, opts.src_rank, self._rank)

    def destroy_group(self):
        self._jit_cache.clear()
        # Drop rendezvous keys so the group name is cleanly reusable.
        gen = getattr(self, "_gen", "")
        keys = [f"{self._group_name}/{gen}/proc/{self._rank}",
                f"{self._group_name}/{gen}/confirm/{self._rank}",
                f"{self._group_name}/{gen}/pre/{self._rank}",
                f"{self._group_name}/{gen}/coordinator"]
        # The {name}/gen pointer is deliberately NOT deleted: a
        # compare-and-delete over plain KV round-trips can race a
        # concurrent re-creation's rotation and wipe the NEW pointer
        # (stranding its late joiners). A stale pointer is harmless —
        # the next creation's rank 0 rotates it unconditionally, and
        # readers are guarded by the own-pre-key discriminator.
        for key in keys:
            try:
                _kv().gcs_request("kv_del", key=key, namespace=_KV_NS)
            except Exception:
                pass
