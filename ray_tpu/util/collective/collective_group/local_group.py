"""Degenerate world_size==1 group (useful for tests and for code written
against the collective API running unsharded)."""

from __future__ import annotations

import numpy as np

from ..types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)
from .base_collective_group import BaseGroup


class LocalGroup(BaseGroup):
    @classmethod
    def backend(cls) -> str:
        return "local"

    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        return np.asarray(tensor)

    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        return np.asarray(tensor)[None]

    def reducescatter(self, tensor,
                      opts: ReduceScatterOptions = ReduceScatterOptions()):
        return np.asarray(tensor)

    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        return np.asarray(tensor)

    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        return np.asarray(tensor)

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        pass

    def send(self, tensor, opts: SendOptions):
        raise ValueError("send/recv undefined for world_size == 1")

    def recv(self, tensor, opts: RecvOptions):
        raise ValueError("send/recv undefined for world_size == 1")
