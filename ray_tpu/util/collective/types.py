"""Collective types (reference: python/ray/util/collective/types.py).

The reference's backends are NCCL (GPU) and GLOO (CPU host nets); the
TPU-native backends are:

* ``xla``  — device collectives compiled by XLA: on TPU they ride ICI/DCN,
  on CPU they ride the jax.distributed gRPC transport. This replaces both
  NCCL (device data) and GLOO (the CPU test mirror) with ONE code path, the
  pattern SURVEY.md §4 calls out (same test matrix on CPU jax backend vs
  real ICI).
* ``local`` — degenerate single-process group for world_size == 1.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from datetime import timedelta


class Backend:
    """Backend name constants (reference: types.py:29-41 Backend enum)."""

    XLA = "xla"
    LOCAL = "local"
    # Aliases accepted for reference compatibility; both map to xla.
    NCCL = "xla"
    GLOO = "xla"

    def __new__(cls, name: str = "xla"):
        name = (name or "xla").lower()
        if name in ("xla", "nccl", "gloo", "tpu", "ici"):
            return "xla"
        if name == "local":
            return "local"
        raise ValueError(f"Unsupported collective backend: {name}")


class ReduceOp(enum.IntEnum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3


unset_timeout = timedelta(milliseconds=-1)


@dataclass
class AllReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout: timedelta = unset_timeout


@dataclass
class BarrierOptions:
    timeout: timedelta = unset_timeout


@dataclass
class ReduceOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout: timedelta = unset_timeout


@dataclass
class AllGatherOptions:
    timeout: timedelta = unset_timeout


@dataclass
class BroadcastOptions:
    src_rank: int = 0
    timeout: timedelta = unset_timeout


@dataclass
class ReduceScatterOptions:
    reduceOp: ReduceOp = ReduceOp.SUM
    timeout: timedelta = unset_timeout


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout: timedelta = unset_timeout


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout: timedelta = unset_timeout
