"""Client-side proxy objects (reference: util/client/common.py —
ClientObjectRef, ClientActorHandle, ClientRemoteFunc)."""
from typing import Any, Optional


class ClientObjectRef:
    def __init__(self, conn, ref_id: str):
        self._conn = conn
        self.ref_id = ref_id

    def __repr__(self):
        return f"ClientObjectRef({self.ref_id[:16]})"

    def __del__(self):
        # Unpin the server-side ref so long-lived sessions don't
        # accumulate every result object (server 'release' op).
        conn = getattr(self, "_conn", None)
        if conn is not None:
            conn._release(self.ref_id)


class ClientRemoteFunction:
    def __init__(self, conn, fn_id: str, name: str, opts=None):
        self._conn = conn
        self._fn_id = fn_id
        self._opts = opts or {}
        self.__name__ = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        return self._conn._call("task", fn_id=self._fn_id,
                                args=args, kwargs=kwargs,
                                opts=self._opts)

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._conn, self._fn_id,
                                    self.__name__, opts)


class _ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        return self._handle._conn._call(
            "actor_method", actor_id=self._handle.actor_id,
            method=self._name, args=args, kwargs=kwargs)


class ClientActorHandle:
    def __init__(self, conn, actor_id: str):
        self._conn = conn
        self.actor_id = actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientActorMethod(self, name)


class ClientActorClass:
    def __init__(self, conn, cls_id: str, name: str):
        self._conn = conn
        self._cls_id = cls_id
        self.__name__ = name

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        return self._conn._create_actor(self._cls_id, args, kwargs)
