"""Remote-driver client (Ray Client equivalent).

Reference parity: python/ray/util/client/ (+ARCHITECTURE.md) — a thin
driver on a laptop proxies `ray.*` calls over the wire to a server
inside the cluster (util/client/server/server.py RayletServicer,
protobuf/ray_client.proto). Here: a multiprocessing.connection listener
in the cluster process; the client ships cloudpickled functions/classes
and holds ClientObjectRef/ClientActorHandle ids. Device data never
crosses this link — only host args/results (the reference has the same
property: the client is control-plane).

Auth: the channel is pickle-based, so connections authenticate with the
per-cluster random token (printed by `ray_tpu start`, or
`state.current().cluster_token.hex()` in the head process).

Server:  from ray_tpu.util.client import server
         server.serve("127.0.0.1", 20001)          # in-cluster process
Client:  import ray_tpu.util.client as client
         conn = client.connect("127.0.0.1:20001", token="<token hex>")
         # (or set RAY_TPU_CLUSTER_TOKEN_HEX and omit token=)
         ref = conn.remote(fn).remote(args)
         conn.get(ref)
"""
from .common import (ClientActorHandle, ClientObjectRef,
                     ClientRemoteFunction)
from .client import ClientConnection, connect
from . import server

__all__ = ["ClientActorHandle", "ClientConnection", "ClientObjectRef",
           "ClientRemoteFunction", "connect", "server"]
