"""Client side of the remote driver (reference: util/client/worker.py —
the Worker that proxies API calls over the channel)."""
import threading
import uuid
from multiprocessing.connection import Client as _MpClient
from typing import Any, List, Optional, Union

import cloudpickle

from .common import (ClientActorClass, ClientActorHandle, ClientObjectRef,
                     ClientRemoteFunction)


def _resolve_token(token) -> bytes:
    if token is not None:
        return bytes.fromhex(token) if isinstance(token, str) else token
    import os
    env = os.environ.get("RAY_TPU_CLUSTER_TOKEN_HEX")
    if env:
        return bytes.fromhex(env)
    # Same-process fallback: a driver that also hosts the cluster.
    from ..._private import state
    rt = state.get_node()
    t = getattr(rt, "cluster_token", None)
    if t is not None:
        return t
    raise RuntimeError(
        "connecting to a ray_tpu cluster requires its token: pass "
        "token=..., or set RAY_TPU_CLUSTER_TOKEN_HEX (printed by "
        "`ray_tpu start`)")


class ClientConnection:
    def __init__(self, address: str, token=None,
                 reconnect_attempts: int = 20,
                 reconnect_backoff_s: float = 0.25):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._authkey = _resolve_token(token)
        self._reconnect_attempts = int(reconnect_attempts)
        self._reconnect_backoff_s = float(reconnect_backoff_s)
        self._conn = _MpClient(self._addr, family="AF_INET",
                               authkey=self._authkey)
        self._lock = threading.Lock()
        # Refs released by ClientObjectRef.__del__ queue here and piggyback
        # on the next request: __del__ can fire from cyclic GC *inside*
        # _request (during cloudpickle) on the same thread, where a
        # synchronous release would deadlock on the non-reentrant _lock
        # (the reference routes releases through a background datapath for
        # the same reason, util/client/dataclient.py).
        self._pending_releases: list = []
        # fn/class registrations, replayed onto a restarted head so the
        # SAME driver session keeps working after a head crash
        # (reference: gcs_client_reconnection_test.cc — clients
        # re-establish and continue).
        self._registrations: list = []  # (op, id_kw, id_val, blob)
        self._closed = False
        assert self._request("ping")["ok"]

    # -- plumbing ----------------------------------------------------------
    def _reconnect_locked(self):
        """Re-dial the head with exponential backoff (caller holds
        _lock). Raises HeadConnectionError when attempts run out."""
        import time as _time

        from ...exceptions import HeadConnectionError
        try:
            self._conn.close()
        except Exception:
            pass
        delay = self._reconnect_backoff_s
        for _ in range(self._reconnect_attempts):
            _time.sleep(min(delay, 5.0))
            delay *= 2
            try:
                conn = _MpClient(self._addr, family="AF_INET",
                                 authkey=self._authkey)
                # Replay session state the restarted head lost.
                for op, id_kw, id_val, blob in self._registrations:
                    conn.send_bytes(cloudpickle.dumps(
                        {"op": op, id_kw: id_val, "blob": blob}))
                    cloudpickle.loads(conn.recv_bytes())
                self._conn = conn
                return
            except Exception:
                continue
        raise HeadConnectionError(
            f"head at {self._addr[0]}:{self._addr[1]} unreachable after "
            f"{self._reconnect_attempts} reconnect attempts")

    def _request(self, op: str, **payload) -> dict:
        from ...exceptions import HeadConnectionError
        payload["op"] = op
        drained: list = []
        if self._pending_releases:
            drained, self._pending_releases = self._pending_releases, []
            payload["__releases__"] = drained
        with self._lock:
            try:
                self._conn.send_bytes(cloudpickle.dumps(payload))
                result = cloudpickle.loads(self._conn.recv_bytes())
            except (EOFError, OSError) as e:
                if drained:
                    # The piggybacked releases died with the request; on
                    # a transient drop the head is still holding those
                    # objects — re-queue them for the next call.
                    self._pending_releases = drained + \
                        self._pending_releases
                if self._closed or self._reconnect_attempts <= 0:
                    raise
                # The head died mid-call. Reconnect for FUTURE calls,
                # but fail THIS one with a typed error: whether the op
                # applied is unknowable, so a silent replay could
                # double-execute it.
                self._reconnect_locked()
                raise HeadConnectionError(
                    f"head connection lost during {op!r}; reconnected — "
                    f"in-flight results were lost, retry the call"
                ) from e
        if not result.pop("__ok__", False):
            raise RuntimeError(
                f"client call failed: {result.get('error')}\n"
                f"{result.get('traceback', '')}")
        return result

    @staticmethod
    def _strip(args, kwargs):
        def conv(a):
            if isinstance(a, ClientObjectRef):
                return {"__client_ref__": True, "ref_id": a.ref_id}
            return a
        return (tuple(conv(a) for a in args),
                {k: conv(v) for k, v in kwargs.items()})

    def _call(self, op: str, *, args=(), kwargs=None, **extra
              ) -> ClientObjectRef:
        args, kwargs = self._strip(args, kwargs or {})
        out = self._request(op, args=args, kwargs=kwargs, **extra)
        return ClientObjectRef(self, out["ref_id"])

    def _create_actor(self, cls_id: str, args, kwargs) -> ClientActorHandle:
        args, kwargs = self._strip(args, kwargs or {})
        out = self._request("create_actor", cls_id=cls_id, args=args,
                            kwargs=kwargs)
        return ClientActorHandle(self, out["actor_id"])

    # -- API (mirrors ray_tpu.*) ------------------------------------------
    def remote(self, target) -> Union[ClientRemoteFunction,
                                      ClientActorClass]:
        blob = cloudpickle.dumps(target)
        if isinstance(target, type):
            cls_id = f"c_{uuid.uuid4().hex}"
            self._request("register_class", cls_id=cls_id, blob=blob)
            self._registrations.append(
                ("register_class", "cls_id", cls_id, blob))
            return ClientActorClass(self, cls_id, target.__name__)
        fn_id = f"f_{uuid.uuid4().hex}"
        self._request("register_fn", fn_id=fn_id, blob=blob)
        self._registrations.append(("register_fn", "fn_id", fn_id, blob))
        return ClientRemoteFunction(self, fn_id, target.__name__)

    def get(self, refs: Union[ClientObjectRef, List[ClientObjectRef]],
            *, timeout: Optional[float] = None) -> Any:
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        out = self._request("get", ref_ids=[r.ref_id for r in ref_list],
                            timeout=timeout)
        values = cloudpickle.loads(out["values"])
        return values[0] if single else values

    def put(self, value: Any) -> ClientObjectRef:
        out = self._request("put", blob=cloudpickle.dumps(value))
        return ClientObjectRef(self, out["ref_id"])

    def api_call(self, name: str, *args, **kwargs) -> Any:
        """Run a whitelisted API op (api_ops.registry) on the head."""
        out = self._request("api_call", name=name, args=args,
                            kwargs=kwargs)
        return cloudpickle.loads(out["value"])

    def _release(self, ref_id: str):
        try:
            self._pending_releases.append(ref_id)
        except Exception:
            pass  # interpreter teardown

    def close(self):
        self._closed = True
        try:
            self._conn.close()
        except Exception:
            pass


def connect(address: str, token=None) -> ClientConnection:
    """Reference: ray.init("ray://host:port") client-mode entry.
    `token`: the cluster token hex (or bytes) printed by `ray_tpu
    start`; defaults to RAY_TPU_CLUSTER_TOKEN_HEX or the in-process
    cluster's token."""
    return ClientConnection(address, token=token)
