"""In-cluster client server (reference: util/client/server/server.py
RayletServicer — executes proxied ray.* calls in the cluster on behalf
of remote drivers; one session's refs/actors are tracked and released
on disconnect)."""
import threading
import traceback
from multiprocessing.connection import Listener
from typing import Any, Dict

import cloudpickle

import ray_tpu


def _cluster_authkey() -> bytes:
    """Per-cluster random token (the same one node daemons use) —
    replaces the round-1 hardcoded key, which made the pickle channel an
    open RCE to anyone who could reach the socket (VERDICT r1 weak #9).
    Remote drivers obtain it from the head's startup banner or
    RAY_TPU_CLUSTER_TOKEN_HEX."""
    from ..._private import state
    rt = state.get_node()
    token = getattr(rt, "cluster_token", None)
    if token is not None:
        return token
    import os
    env = os.environ.get("RAY_TPU_CLUSTER_TOKEN_HEX")
    if env:
        return bytes.fromhex(env)
    raise RuntimeError("client server needs an initialized runtime "
                       "(cluster token) or RAY_TPU_CLUSTER_TOKEN_HEX")


class _Session:
    """Per-connection state (reference: server-side per-client tracking)."""

    def __init__(self):
        self.fns: Dict[str, Any] = {}        # fn_id -> RemoteFunction
        self.classes: Dict[str, Any] = {}    # cls_id -> ActorClass
        self.refs: Dict[str, Any] = {}       # ref_id -> ObjectRef
        self.actors: Dict[str, Any] = {}     # actor_id -> ActorHandle

    def release_all(self):
        for a in self.actors.values():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self.refs.clear()
        self.actors.clear()


def _handle(session: _Session, op: str, payload: Dict[str, Any]):
    if op == "ping":
        return {"ok": True}
    if op == "register_fn":
        fn = cloudpickle.loads(payload["blob"])
        rf = ray_tpu.remote(fn)
        session.fns[payload["fn_id"]] = rf
        return {"ok": True}
    if op == "register_class":
        cls = cloudpickle.loads(payload["blob"])
        session.classes[payload["cls_id"]] = ray_tpu.remote(cls)
        return {"ok": True}
    if op == "task":
        rf = session.fns[payload["fn_id"]]
        opts = payload.get("opts") or {}
        if opts:
            rf = rf.options(**opts)
        args, kwargs = _resolve(session, payload)
        ref = rf.remote(*args, **kwargs)
        session.refs[ref.hex()] = ref
        return {"ref_id": ref.hex()}
    if op == "create_actor":
        cls = session.classes[payload["cls_id"]]
        args, kwargs = _resolve(session, payload)
        handle = cls.remote(*args, **kwargs)
        aid = handle._id.hex()
        session.actors[aid] = handle
        return {"actor_id": aid}
    if op == "actor_method":
        handle = session.actors[payload["actor_id"]]
        args, kwargs = _resolve(session, payload)
        ref = getattr(handle, payload["method"]).remote(*args, **kwargs)
        session.refs[ref.hex()] = ref
        return {"ref_id": ref.hex()}
    if op == "get":
        refs = [session.refs[r] for r in payload["ref_ids"]]
        values = ray_tpu.get(refs, timeout=payload.get("timeout"))
        return {"values": cloudpickle.dumps(values)}
    if op == "put":
        ref = ray_tpu.put(cloudpickle.loads(payload["blob"]))
        session.refs[ref.hex()] = ref
        return {"ref_id": ref.hex()}
    if op == "release":
        session.refs.pop(payload["ref_id"], None)
        return {"ok": True}
    if op == "api_call":
        from .api_ops import registry
        fn = registry().get(payload["name"])
        if fn is None:
            raise ValueError(f"unknown api op {payload['name']!r}")
        value = fn(*payload.get("args", ()), **payload.get("kwargs", {}))
        return {"value": cloudpickle.dumps(value)}
    raise ValueError(f"unknown op {op}")


def _resolve(session: _Session, payload):
    """Client refs in args become server-side ObjectRefs."""
    from .common import ClientObjectRef

    def conv(a):
        if isinstance(a, dict) and a.get("__client_ref__"):
            return session.refs[a["ref_id"]]
        return a

    args = tuple(conv(a) for a in payload.get("args", ()))
    kwargs = {k: conv(v) for k, v in payload.get("kwargs", {}).items()}
    return args, kwargs


def _serve_conn(conn):
    session = _Session()
    try:
        while True:
            try:
                raw = conn.recv_bytes()
            except (EOFError, OSError):
                break
            try:
                msg = cloudpickle.loads(raw)
            except Exception as e:  # noqa: BLE001 — bad payload: reply,
                # keep the session alive (don't kill the client's actors)
                try:
                    conn.send_bytes(cloudpickle.dumps(
                        {"__ok__": False,
                         "error": f"undeserializable request: {e!r}",
                         "traceback": traceback.format_exc()}))
                    continue
                except (EOFError, OSError):
                    break
            try:
                for rid in msg.pop("__releases__", ()):
                    session.refs.pop(rid, None)
                result = _handle(session, msg["op"], msg)
                result["__ok__"] = True
            except Exception as e:  # noqa: BLE001
                result = {"__ok__": False, "error": repr(e),
                          "traceback": traceback.format_exc()}
            conn.send_bytes(cloudpickle.dumps(result))
    finally:
        session.release_all()
        conn.close()


def serve(host: str = "127.0.0.1", port: int = 0,
          blocking: bool = False):
    """Start the client server; returns (host, port). The cluster must be
    init()ed in this process."""
    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    listener = Listener((host, port), family="AF_INET",
                        authkey=_cluster_authkey())
    bound = listener.address

    def _accept_loop():
        while True:
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                break
            threading.Thread(target=_serve_conn, args=(conn,),
                             daemon=True).start()

    t = threading.Thread(target=_accept_loop, daemon=True,
                         name="client-server")
    t.start()
    if blocking:
        t.join()
    return bound
