"""Named API operations callable locally or through the client server.

Reference parity: the Ray Client server proxies `ray.*` and state/job
API calls for remote drivers (util/client/server/server.py
RayletServicer); this registry is the whitelist of proxied operations —
the CLI uses the same names against either a local runtime or a remote
head (`--address`), so `ray_tpu status` reflects the actual cluster it
points at.
"""

from __future__ import annotations

from typing import Any, Callable, Dict


def registry() -> Dict[str, Callable[..., Any]]:
    import ray_tpu
    from ray_tpu.job import JobSubmissionClient
    from ray_tpu.util import state

    def job_client() -> JobSubmissionClient:
        return JobSubmissionClient()

    return {
        "cluster_resources": ray_tpu.cluster_resources,
        "available_resources": ray_tpu.available_resources,
        "list_nodes": state.list_nodes,
        "list_tasks": state.list_tasks,
        "list_actors": state.list_actors,
        "list_objects": state.list_objects,
        "list_workers": state.list_workers,
        "list_placement_groups": state.list_placement_groups,
        # Graceful drain (docs/DRAIN.md): runs ON the head — the CLI can
        # fire-and-poll a drain against a remote cluster.
        "drain_node": state.drain_node,
        "drain_status": state.drain_status,
        "summarize_tasks": state.summarize_tasks,
        "summarize_actors": state.summarize_actors,
        "summarize_objects": state.summarize_objects,
        "timeline": lambda: state.timeline(filename=None),
        "cluster_metrics": _cluster_metrics,
        # Tracing consumers (PR 7): cross-node trace tree + the merged
        # chrome export, served from the head's span store.
        "get_trace": _get_trace,
        "export_chrome_trace": _export_chrome_trace,
        "job_submit": lambda **kw: job_client().submit_job(**kw),
        "job_status": lambda job_id: job_client().get_job_status(job_id),
        "job_logs": lambda job_id: job_client().get_job_logs(job_id),
        "job_list": lambda: job_client().list_jobs(),
        "job_stop": lambda job_id: job_client().stop_job(job_id),
        # Serve control plane (reference: serve CLI → controller REST):
        # deploy runs ON the head, so apps outlive the CLI process.
        "serve_deploy": _serve_deploy,
        "serve_status": _serve_status,
        "serve_shutdown": _serve_shutdown,
    }


def _get_trace(trace_id: str) -> dict:
    from ray_tpu.util import tracing
    return tracing.get_trace(trace_id)


def _export_chrome_trace(trace_id=None) -> list:
    from ray_tpu.util import tracing
    return tracing.export_chrome_trace(filename=None, trace_id=trace_id)


def _cluster_metrics() -> str:
    """Federated Prometheus text (telemetry.py): head registry + every
    node's / worker's latest pushed snapshot, node/worker tagged."""
    from ray_tpu._private.telemetry import cluster_metrics_text
    return cluster_metrics_text()


def _serve_deploy(config: dict):
    from ray_tpu.serve import schema as serve_schema
    return serve_schema.deploy_config(
        serve_schema.ServeDeploySchema.from_dict(config))


def _serve_status():
    from ray_tpu import serve
    return serve.status()


def _serve_shutdown():
    from ray_tpu import serve
    serve.shutdown()
    return True
