"""User-defined metrics: Counter / Gauge / Histogram.

Reference parity: python/ray/util/metrics.py (Counter, Gauge,
Histogram over includes/metric.pxi; C++ defs src/ray/stats/metric.h:103)
+ the Prometheus exposition the per-node MetricsAgent provides
(_private/metrics_agent.py:483, prometheus_exporter.py).

Process-local registry; `prometheus_text()` renders the standard text
format, `start_metrics_server(port)` serves it on /metrics so a scraper
(or the dashboard) can pull from each process.
"""
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_REG_LOCK = threading.Lock()


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base (reference: util/metrics.py Metric)."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name or not name.replace("_", "").isalnum():
            raise ValueError(f"Invalid metric name {name!r}")
        self._name = name
        self._desc = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}
        with _REG_LOCK:
            _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        extra = set(merged) - set(self._tag_keys)
        if extra:
            raise ValueError(
                f"Unknown tag(s) {sorted(extra)} for metric {self._name}; "
                f"declared tag_keys={self._tag_keys}")
        return merged

    @property
    def info(self) -> Dict:
        return {"name": self._name, "description": self._desc,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}

    def _samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        with self._lock:
            return [(self._name, dict(k), v)
                    for k, v in self._values.items()]


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires value > 0")
        key = _tag_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict] = None):
        key = _tag_key(self._merged(tags))
        with self._lock:
            self._values[key] = float(value)


class _HistogramHandle:
    """Precomputed tag handle of one Histogram series: the per-request
    hot path skips the tag-merge/validate/sort of `observe()` and bins
    with a bisect into per-shard counters (shard picked by thread id,
    so concurrent request threads never contend on one lock). Shards
    merge at sample time — the exposition output is identical to the
    classic path."""

    __slots__ = ("_bounds", "_shards", "_locks")

    _N_SHARDS = 4

    def __init__(self, bounds: List[float]):
        self._bounds = bounds
        nb = len(bounds)
        # shard := [bucket_0..bucket_n-1, sum, total]
        self._shards = [[0.0] * (nb + 2) for _ in range(self._N_SHARDS)]
        self._locks = [threading.Lock() for _ in range(self._N_SHARDS)]

    def observe(self, value: float) -> None:
        from bisect import bisect_left
        # >> 12: on Linux CPython get_ident() is the pthread stack
        # address, aligned well past 4 KiB — a bare modulo would map
        # EVERY thread to shard 0 and resurrect the single-lock
        # contention this handle exists to remove.
        i = (threading.get_ident() >> 12) % self._N_SHARDS
        shard = self._shards[i]
        b = bisect_left(self._bounds, value)
        with self._locks[i]:
            if b < len(self._bounds):
                shard[b] += 1
            shard[-2] += value
            shard[-1] += 1

    def _merged_totals(self):
        nb = len(self._bounds)
        counts = [0.0] * nb
        total = 0.0
        vsum = 0.0
        for i, shard in enumerate(self._shards):
            with self._locks[i]:
                snap = list(shard)
            for j in range(nb):
                counts[j] += snap[j]
            vsum += snap[-2]
            total += snap[-1]
        return counts, vsum, total


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        if not boundaries:
            boundaries = [0.1, 1.0, 10.0]
        if any(b <= 0 for b in boundaries):
            raise ValueError(
                f"Histogram boundaries must be positive, got {boundaries}")
        self._bounds = sorted(float(b) for b in boundaries)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}
        self._handles: Dict[Tuple, _HistogramHandle] = {}

    def observe(self, value: float, tags: Optional[Dict] = None):
        key = _tag_key(self._merged(tags))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self._bounds))
            for i, b in enumerate(self._bounds):
                if value <= b:
                    counts[i] += 1
                    break
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def handle(self, tags: Optional[Dict] = None) -> _HistogramHandle:
        """Resolve one tag combination ONCE; the returned handle's
        `observe(value)` is the cheap per-request form (no tag dict, no
        merge/sort, sharded bins). Cache the handle at the call site."""
        key = _tag_key(self._merged(tags))
        with self._lock:
            h = self._handles.get(key)
            if h is None:
                h = self._handles[key] = _HistogramHandle(self._bounds)
        return h

    def _samples(self):
        out = []
        with self._lock:
            series: Dict[Tuple, Tuple[List[float], float, float]] = {}
            for key, counts in self._counts.items():
                series[key] = ([float(c) for c in counts],
                               self._sums.get(key, 0.0),
                               float(self._totals.get(key, 0)))
            handles = list(self._handles.items())
        for key, h in handles:
            counts, vsum, total = h._merged_totals()
            if key in series:
                base = series[key]
                series[key] = ([a + b for a, b in zip(base[0], counts)],
                               base[1] + vsum, base[2] + total)
            else:
                series[key] = (counts, vsum, total)
        for key, (counts, vsum, total) in series.items():
            tags = dict(key)
            cum = 0.0
            for b, c in zip(self._bounds, counts):
                cum += c
                out.append((f"{self._name}_bucket",
                            {**tags, "le": str(b)}, float(cum)))
            out.append((f"{self._name}_bucket",
                        {**tags, "le": "+Inf"}, float(total)))
            out.append((f"{self._name}_sum", tags, vsum))
            out.append((f"{self._name}_count", tags, float(total)))
        return out


def escape_label_value(value) -> str:
    """Prometheus exposition-format label-value escaping (backslash,
    quote, newline) — tag values can carry user-controlled strings
    (deployment names, routes), and one bad character must not break
    the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_sample(name: str, tags: Optional[Dict[str, str]],
                  value) -> str:
    """Render ONE exposition sample line — the single formatter shared
    by the process-local text endpoint and the cluster-wide federation
    (_private/telemetry.py)."""
    if tags:
        tag_s = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in sorted(tags.items()))
        return f"{name}{{{tag_s}}} {value}"
    return f"{name} {value}"


def prometheus_text() -> str:
    """Standard Prometheus text exposition of all registered metrics
    (reference: _private/prometheus_exporter.py)."""
    lines = []
    with _REG_LOCK:
        metrics = list(_REGISTRY.values())
    for m in metrics:
        lines.append(f"# HELP {m._name} {m._desc}")
        lines.append(f"# TYPE {m._name} {m.TYPE}")
        for name, tags, value in m._samples():
            lines.append(format_sample(name, tags, value))
    return "\n".join(lines) + "\n"


def registry_samples() -> List[Dict]:
    """Picklable snapshot of every registered metric — the unit of the
    cluster-wide metric federation (reference: what the per-node
    MetricsAgent scrapes from each process). Each entry:
    ``{"name", "type", "help", "samples": [(name, tags, value), ...]}``;
    daemons ship this on heartbeats and workers piggyback it on task
    completion (_private/telemetry.py), and the head re-exports the
    merged set with node_id/worker_id tags."""
    with _REG_LOCK:
        ms = list(_REGISTRY.values())
    out = []
    for m in ms:
        try:
            samples = m._samples()
        except Exception:
            continue
        out.append({"name": m._name, "type": m.TYPE, "help": m._desc,
                    "samples": samples})
    return out


_server = None


def start_metrics_server(port: int = 0, host: str = "127.0.0.1") -> int:
    """Serve /metrics for Prometheus scraping; returns the bound port."""
    global _server
    if _server is not None:
        return _server.server_address[1]
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    _server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_server.serve_forever, daemon=True,
                     name="metrics-server").start()
    return _server.server_address[1]


def stop_metrics_server():
    global _server
    if _server is not None:
        _server.shutdown()
        _server.server_close()
        _server = None


# Callbacks run when the registry is cleared, so caches holding
# per-metric handles (telemetry's serve histogram handles) drop them
# instead of observing into orphaned, unregistered metrics forever.
_on_clear: List = []


def on_clear_registry(cb) -> None:
    _on_clear.append(cb)


def clear_registry():
    with _REG_LOCK:
        _REGISTRY.clear()
    for cb in list(_on_clear):
        try:
            cb()
        except Exception:
            pass


__all__ = ["Counter", "Gauge", "Histogram", "Metric", "clear_registry",
           "escape_label_value", "format_sample", "prometheus_text",
           "registry_samples", "start_metrics_server",
           "stop_metrics_server"]
