"""(reference: python/ray/util/lightgbm/__init__.py — removed in Ray 2.0
in favor of Train's LightGBMTrainer; the parity surface is the same
redirect.)"""

raise DeprecationWarning(
    "ray_tpu.util.lightgbm mirrors ray.util.lightgbm, which was removed "
    "as of Ray 2.0. Use ray_tpu.train.LightGBMTrainer instead."
)
