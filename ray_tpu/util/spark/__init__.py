"""Cluster-on-Spark launcher surface (reference: python/ray/util/spark/
— setup_ray_cluster/shutdown_ray_cluster run Ray nodes inside Spark
executors via a barrier-mode job, cluster_init.py).

Spark-hosted provisioning is a cloud-integration concern out of the
single-host runtime's scope; the surface exists so callers get a clear
error (and the autoscaler's provider plugin API —
autoscaler/node_provider.py — is the supported path for custom
provisioning)."""

from typing import Any

__all__ = ["setup_ray_cluster", "shutdown_ray_cluster"]


def setup_ray_cluster(*args: Any, **kwargs: Any):
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "ray_tpu.util.spark requires `pyspark` to be installed."
        ) from e
    raise NotImplementedError(
        "Spark-hosted clusters are not implemented in this build; "
        "implement a NodeProvider (ray_tpu.autoscaler.node_provider) "
        "that launches hosts via your Spark deployment instead.")


def shutdown_ray_cluster():
    raise NotImplementedError(
        "Spark-hosted clusters are not implemented in this build.")
