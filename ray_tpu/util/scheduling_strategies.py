"""Scheduling strategies (reference parity:
python/ray/util/scheduling_strategies.py).

``PlacementGroupSchedulingStrategy`` pins a task/actor into a placement
group's reserved bundles; ``NodeAffinitySchedulingStrategy`` targets a
specific node. On the single-resource-view runtime node affinity is
trivially satisfied for the local node id and infeasible otherwise (hard)
or ignored (soft)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    """Schedule into a placement group's reserved bundles.

    Reference: util/scheduling_strategies.py PlacementGroupSchedulingStrategy.
    """

    def __init__(self, placement_group,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: Optional[bool] = None):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks)

    def __repr__(self):
        return (f"PlacementGroupSchedulingStrategy(pg="
                f"{self.placement_group.id[:8]}, bundle="
                f"{self.placement_group_bundle_index})")


class NodeAffinitySchedulingStrategy:
    """Reference: util/scheduling_strategies.py NodeAffinitySchedulingStrategy."""

    def __init__(self, node_id: str, soft: bool = False,
                 _spill_on_unavailable: bool = False,
                 _fail_on_unavailable: bool = False):
        self.node_id = node_id
        self.soft = soft
        self._spill_on_unavailable = _spill_on_unavailable
        self._fail_on_unavailable = _fail_on_unavailable


class In:
    def __init__(self, *values):
        self.values = list(values)


class NotIn:
    def __init__(self, *values):
        self.values = list(values)


class Exists:
    pass


class DoesNotExist:
    pass


class NodeLabelSchedulingStrategy:
    """Reference: util/scheduling_strategies.py NodeLabelSchedulingStrategy
    (hard/soft label expressions)."""

    def __init__(self, hard: Optional[dict] = None,
                 soft: Optional[dict] = None):
        self.hard = hard or {}
        self.soft = soft or {}
