"""Accelerator type constants (reference:
python/ray/util/accelerators/accelerators.py:22-25 TPU type constants,
used with `accelerator_type=` on tasks/actors for type-affinity
scheduling)."""

TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5P = "TPU-V5P"
TPU_V5LITEPOD = "TPU-V5LITEPOD"
TPU_V6E = "TPU-V6E"

# chips per host by generation (standard TPU VM topologies)
TPU_CHIPS_PER_HOST = {
    TPU_V2: 4, TPU_V3: 4, TPU_V4: 4, TPU_V5P: 4,
    TPU_V5LITEPOD: 8, TPU_V6E: 8,
}

ALL_TPU_TYPES = tuple(TPU_CHIPS_PER_HOST)


def chips_per_host(accel_type: str) -> int:
    return TPU_CHIPS_PER_HOST.get(accel_type, 4)


def pod_slice_head_resource(accel_type: str, total_chips: int) -> str:
    """`TPU-<ver>-<chips>-head` gang resource (reference: tpu.py:330-377)."""
    return f"{accel_type}-{total_chips}-head"


def pod_slice_num_hosts(accel_type: str, total_chips: int) -> int:
    return max(1, total_chips // chips_per_host(accel_type))
