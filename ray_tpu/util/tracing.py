"""Distributed tracing: a first-class citizen of the telemetry plane.

Reference parity: python/ray/util/tracing/tracing_helper.py — the
reference injects OpenTelemetry spans around task/actor submission and
execution and propagates span context *inside task specs*
(_DictPropagator:165, span decorators :195+), and aggregates per-task
events in the GCS task manager (SURVEY §2.2, §5). Same design here
without a hard OpenTelemetry dependency.

Architecture (PR 7 — everything piggybacks on the telemetry plane):

  * **Recording** is a lock + bounded deque append into a process-local
    drop-oldest buffer with an EXACT drop counter — never a syscall,
    never a head round trip (the old ``record_spans`` gcs_request flush
    after every traced task is gone).
  * **Shipping**: workers drain the buffer into the ``TASK_EVENTS``
    frame enqueued right before each completion (worker_proc
    ``_flush_telemetry``), so spans ride the SAME vectored write as the
    TASK_DONE — zero extra syscalls; idle workers drain on the
    TELEMETRY_DRAIN heartbeat nudge. The driver flushes straight into
    the in-process store.
  * **Aggregation**: ``Gcs.telemetry`` keeps bounded per-trace rings
    (``TelemetryStore.record_spans``) with per-trace drop counters and
    a global LRU cap — replacing the old unbounded ``Gcs._spans`` list.
  * **Propagation**: submit spans stamp ``spec.trace_ctx`` (api.py);
    the direct plane carries the context as a compact-wire tail slot
    (traced calls keep the no-arg fast path); the serve proxy speaks
    W3C ``traceparent`` in and out.

Gate discipline: ``tracing.enabled`` is a module attribute (falsy-flag,
like ``telemetry.enabled`` / ``fault.enabled``); every helper that does
tracing work bumps the ``_ops`` counter so the tracing-off hot path is
provably zero-work (counter-based perf_smoke guard). ``enable()``
mirrors the flag into ``RAY_TPU_TRACING`` so spawned daemons, workers,
and serve replicas inherit it.

Usage:
    from ray_tpu.util import tracing
    tracing.enable()
    with tracing.span("ingest", source="s3"):
        ref = f.remote(...)        # submit span + context ride the spec
    tracing.get_trace(trace_id)    # cross-node tree + critical path
    tracing.export_chrome_trace("/tmp/trace.json")
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

_ENV_VAR = "RAY_TPU_TRACING"


def _env_enabled() -> bool:
    return os.environ.get(_ENV_VAR, "0").strip().lower() in (
        "1", "true", "yes", "on")


# Hot-path gate: module attribute looked up as `tracing.enabled` (one
# dict lookup); instrumentation sites check it (or an adopted context)
# before doing ANY tracing work. Default OFF (tracing is opt-in, unlike
# telemetry).
enabled = _env_enabled()

# Counter of tracing-helper invocations in THIS process — the
# perf_smoke guard's counter-based proxy for "the disabled path did no
# tracing work" (same discipline as telemetry.instrument_ops).
_ops = 0

_lock = threading.Lock()
# Bounded drop-oldest span buffer (drained by the worker's telemetry
# flush / the driver's in-process flush). Exact accounting: every
# record beyond capacity since the last drain counts in _dropped once.
_buffer: collections.deque = collections.deque()
_dropped = 0

_current: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace", default=None)   # (trace_id, span_id) or None


def _buffer_cap() -> int:
    from .._private.config import ray_config
    return max(16, int(ray_config.span_buffer_size))


def trace_ops() -> int:
    """Tracing-helper invocations so far (perf_smoke guard)."""
    return _ops


def enable(propagate_env: bool = True) -> None:
    """Turn on tracing in this process (reference:
    ray.init(_tracing_startup_hook=...) switch). With ``propagate_env``
    the flag is mirrored into RAY_TPU_TRACING so spawned daemons and
    workers inherit it."""
    global enabled
    enabled = True
    if propagate_env:
        os.environ[_ENV_VAR] = "1"


def disable(propagate_env: bool = True) -> None:
    global enabled
    enabled = False
    if propagate_env:
        os.environ[_ENV_VAR] = "0"


def is_enabled() -> bool:
    """Tracing is on if enabled process-wide OR a propagated context is
    active in this task (workers trace exactly the requests whose
    driver/proxy had tracing on, without flipping process state)."""
    return enabled or _current.get() is not None


def current_context() -> Optional[Dict[str, str]]:
    """Propagatable context dict of the active span (reference:
    _DictPropagator.inject_current_context)."""
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "parent_span_id": cur[1]}


# ---------------------------------------------------------------------------
# W3C traceparent (the serve-proxy wire form of the context)
# ---------------------------------------------------------------------------
def parse_traceparent(header: Optional[str]) -> Optional[Dict[str, str]]:
    """``00-<32hex trace>-<16hex parent>-<2hex flags>`` -> context dict
    (None on anything malformed — a bad client header must never fail
    the request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return {"trace_id": parts[1], "parent_span_id": parts[2]}


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------
def _record(span: dict) -> None:
    """Buffer one finished span: lock + deque append, drop-oldest with
    an exact counter. NO flush round trip here — spans leave the
    process on the telemetry plane's existing frames."""
    global _dropped
    cap = _buffer_cap()
    with _lock:
        if len(_buffer) >= cap:
            _buffer.popleft()
            _dropped += 1
        _buffer.append(span)


def drain_spans() -> Tuple[List[dict], int]:
    """Pop everything buffered; returns (spans, dropped_since_last).
    Called by the worker's telemetry flush (spans ride the TASK_EVENTS
    frame) and by the driver-side flush below."""
    global _dropped
    if not _buffer and not _dropped:
        return [], 0
    with _lock:
        spans = list(_buffer)
        _buffer.clear()
        dropped, _dropped = _dropped, 0
    return spans, dropped


def flush() -> None:
    """Consumer-path flush: move buffered spans into the head's store.
    On the driver this is an in-process call; in a worker it is ONE
    explicit gcs request (reached only from get_spans/get_trace — the
    task hot path ships spans on the TASK_EVENTS piggyback instead).
    Before init the bounded buffer simply holds."""
    if not _buffer and not _dropped:
        return
    from .._private import state
    node = state.get_node()
    if node is not None:
        spans, dropped = drain_spans()
        if spans or dropped:
            node.gcs.record_spans(spans, dropped=dropped,
                                  node_id=node.node_id.hex(),
                                  worker_id="driver")
        return
    rt = state.current_or_none()
    if rt is None or not hasattr(rt, "gcs_request"):
        return
    spans, dropped = drain_spans()
    if spans or dropped:
        # Stamp THIS worker's identity: the head's generic gcs-op path
        # has no sender context, and an unstamped batch would render
        # under the head node / "driver" in the tree.
        kw = {"spans": spans, "dropped": dropped}
        w = getattr(state, "_worker", None)
        if w is not None:
            kw["node_id"] = w.config.node_id_hex
            kw["worker_id"] = w.config.worker_id.hex()
        try:
            rt.gcs_request("record_spans", **kw)
        except Exception:
            # Bounded loss, surfaced: no silent swallow, no unbounded
            # retry re-queue (the old `_buffer = batch + _buffer` bug).
            import logging
            logging.getLogger(__name__).warning(
                "dropping %d spans: head flush failed", len(spans),
                exc_info=True)


@contextlib.contextmanager
def span(name: str, **attributes: Any):
    """Record a span; nests under the active span, and downstream
    task/actor submissions inside it carry the context remotely."""
    global _ops
    if not is_enabled():
        yield None
        return
    _ops += 1
    cur = _current.get()
    trace_id = cur[0] if cur else uuid.uuid4().hex
    span_id = uuid.uuid4().hex[:16]
    token = _current.set((trace_id, span_id))
    start = time.time()
    error = None
    try:
        with _maybe_otel_span(name, attributes):
            yield span_id
    except BaseException as e:
        error = repr(e)
        raise
    finally:
        _current.reset(token)
        _record({
            "name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_span_id": cur[1] if cur else None,
            "start": start, "end": time.time(),
            "attributes": attributes or None, "error": error,
        })


def activate_context(ctx: Optional[Dict[str, str]]):
    """Adopt a propagated context (worker side; reference: extract from
    the task spec before running the user function). Returns a reset
    token or None. Deliberately does NOT flip the process-global enable
    flag: once the context is reset, this worker stops tracing unless
    the next task carries a context too."""
    global _ops
    if not ctx:
        return None
    _ops += 1
    return _current.set((ctx["trace_id"], ctx["parent_span_id"]))


def deactivate_context(token) -> None:
    if token is not None:
        _current.reset(token)


@contextlib.contextmanager
def _maybe_otel_span(name: str, attributes: Dict):
    """Mirror to OpenTelemetry when available (reference:
    _OpenTelemetryProxy:34 — tracing works without it installed)."""
    try:
        from opentelemetry import trace as otel_trace
        tracer = otel_trace.get_tracer("ray_tpu")
    except Exception:
        yield
        return
    with tracer.start_as_current_span(name, attributes={
            k: str(v) for k, v in (attributes or {}).items()}):
        yield


# ---------------------------------------------------------------------------
# collection / consumers (driver side)
# ---------------------------------------------------------------------------
def get_spans(trace_id: Optional[str] = None) -> List[dict]:
    """Spans aggregated in the head's telemetry store (flushing this
    process's buffer first)."""
    flush()
    from .._private import state
    node = state.get_node()
    if node is not None:
        return node.gcs.spans(trace_id)
    rt = state.current_or_none()
    if rt is not None and hasattr(rt, "gcs_request"):
        try:
            # `or []`: local mode answers unknown ops with None.
            return rt.gcs_request("get_spans", trace_id=trace_id) or []
        except Exception:
            return []
    return []


def build_trace(spans: List[dict]) -> dict:
    """Assemble one trace's spans into a tree + critical-path summary.
    Pure function of the span list (unit-testable; get_trace feeds it
    the store's ring)."""
    by_id: Dict[str, dict] = {}
    for s in spans:
        sid = s.get("span_id")
        if sid:
            # First writer wins: a SIGKILL/retry replay of the same
            # span id must not duplicate a node in the tree.
            by_id.setdefault(sid, dict(s, children=[]))
    roots: List[dict] = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_span_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in by_id.values():
        node["children"].sort(key=lambda c: c.get("start", 0.0))
    roots.sort(key=lambda c: c.get("start", 0.0))

    # Critical path: from the earliest root, descend into the child
    # whose END is latest (the chain the trace's wall time waited on).
    path: List[dict] = []
    cur = roots[0] if roots else None
    while cur is not None:
        path.append({
            "name": cur.get("name"), "span_id": cur.get("span_id"),
            "start": cur.get("start"), "end": cur.get("end"),
            "duration_s": round(
                (cur.get("end") or 0.0) - (cur.get("start") or 0.0), 6),
            "node_id": cur.get("node_id"),
            "worker_id": cur.get("worker_id"),
            "error": cur.get("error")})
        kids = cur["children"]
        cur = max(kids, key=lambda c: c.get("end", 0.0)) if kids else None
    starts = [s.get("start") for s in spans if s.get("start") is not None]
    ends = [s.get("end") for s in spans if s.get("end") is not None]
    return {
        "trace_id": spans[0].get("trace_id") if spans else None,
        "span_count": len(by_id),
        "node_ids": sorted({s.get("node_id") for s in spans
                            if s.get("node_id")}),
        "duration_s": round(max(ends) - min(starts), 6)
        if starts and ends else 0.0,
        "roots": roots,
        "critical_path": path,
    }


def get_trace(trace_id: str) -> dict:
    """Reassemble the cross-node span tree of one trace with a
    critical-path summary (reference: what a Jaeger/Zipkin UI renders
    from the collector; the `ray_tpu trace <id>` CLI prints this)."""
    return build_trace(get_spans(trace_id))


def format_trace(trace: dict) -> str:
    """Human-readable tree of a get_trace() result (the CLI's renderer)."""
    lines = [f"trace {trace.get('trace_id')}  "
             f"{trace.get('span_count')} spans  "
             f"{trace.get('duration_s')}s  "
             f"nodes={','.join(n[:8] for n in trace.get('node_ids', []))}"]

    def walk(node, depth):
        dur = (node.get("end") or 0.0) - (node.get("start") or 0.0)
        where = (node.get("worker_id") or "driver")[:8]
        err = "  ERROR" if node.get("error") else ""
        lines.append(f"{'  ' * depth}{node.get('name')}  "
                     f"[{dur * 1000:.2f} ms @ {where}]{err}")
        for c in node.get("children", ()):
            walk(c, depth + 1)

    for r in trace.get("roots", ()):
        walk(r, 1)
    crit = trace.get("critical_path") or ()
    if crit:
        lines.append("critical path: " + " -> ".join(
            f"{s['name']} ({s['duration_s'] * 1000:.2f} ms)"
            for s in crit))
    return "\n".join(lines)


def export_chrome_trace(filename: Optional[str] = None,
                        trace_id: Optional[str] = None) -> List[dict]:
    """Spans + task timeline as ONE Chrome-trace JSON with a shared
    layout — **rows (pid) are nodes, threads (tid) are workers**, the
    same convention as `ray_tpu timeline`, so a serve request's proxy,
    replica, and nested-task spans line up under the workers that ran
    them (reference: `ray timeline` merged with span events)."""
    import json

    from . import state as state_api

    events = state_api.timeline()
    for s in get_spans(trace_id):
        if "ph" in s:
            # Pre-formed chrome event (util/profiling.py records these
            # straight into the span store).
            events.append(s)
            continue
        if s.get("start") is None or s.get("end") is None:
            continue
        events.append({
            "cat": "span", "name": s.get("name") or "?", "ph": "X",
            "ts": s["start"] * 1e6, "dur": (s["end"] - s["start"]) * 1e6,
            "pid": (s.get("node_id") or "ray_tpu")[:8],
            "tid": (s.get("worker_id") or "driver")[:8],
            "args": {k: v for k, v in s.items()
                     if k in ("trace_id", "span_id", "parent_span_id",
                              "attributes", "error")},
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
