"""Distributed tracing: spans + context propagation across tasks/actors.

Reference parity: python/ray/util/tracing/tracing_helper.py — the
reference injects OpenTelemetry spans around task/actor submission and
execution and propagates span context *inside task specs*
(_DictPropagator:165, span decorators :195+). Same design here without a
hard OpenTelemetry dependency: spans are plain dicts buffered per
process, shipped to the GCS-equivalent span store (driver: direct;
workers: piggybacked gcs_request), and exportable as Chrome-trace JSON
alongside the task timeline. If `opentelemetry` is importable, spans are
mirrored to the active OTel tracer.

Usage:
    from ray_tpu.util import tracing
    tracing.enable()
    with tracing.span("ingest", source="s3"):
        ref = f.remote(...)        # submit span + context ride the spec
    tracing.export_chrome_trace("/tmp/trace.json")
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_enabled = False
_lock = threading.Lock()
_buffer: List[dict] = []
# How worker processes flush: set by worker bootstrap to a gcs_request
# closure; None on the driver (writes straight into the Gcs).
_flush_fn = None

_current: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace", default=None)   # (trace_id, span_id) or None


def enable() -> None:
    """Turn on tracing in this process (reference:
    ray.init(_tracing_startup_hook=...) switch)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    """Tracing is on if enabled process-wide OR a propagated context is
    active in this task (workers trace exactly the requests whose driver
    had tracing on, without flipping any process-global state)."""
    return _enabled or _current.get() is not None


def current_context() -> Optional[Dict[str, str]]:
    """Propagatable context dict of the active span (reference:
    _DictPropagator.inject_current_context)."""
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "parent_span_id": cur[1]}


def _record(span: dict) -> None:
    with _lock:
        _buffer.append(span)
        if len(_buffer) >= 128:
            _flush_locked()


def _flush_locked() -> None:
    global _buffer
    if not _buffer:
        return
    batch, _buffer = _buffer, []
    try:
        if _flush_fn is not None:
            _flush_fn(batch)
        else:
            from .._private import state
            rt = state.current_or_none()
            if rt is not None:
                rt.gcs.record_spans(batch)
            else:
                _buffer = batch + _buffer  # no runtime yet; retry later
    except Exception:
        pass


def flush() -> None:
    with _lock:
        _flush_locked()


@contextlib.contextmanager
def span(name: str, **attributes: Any):
    """Record a span; nests under the active span, and downstream
    task/actor submissions inside it carry the context remotely."""
    if not is_enabled():
        yield None
        return
    cur = _current.get()
    trace_id = cur[0] if cur else uuid.uuid4().hex
    span_id = uuid.uuid4().hex[:16]
    token = _current.set((trace_id, span_id))
    start = time.time()
    error = None
    try:
        with _maybe_otel_span(name, attributes):
            yield span_id
    except BaseException as e:
        error = repr(e)
        raise
    finally:
        _current.reset(token)
        _record({
            "name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_span_id": cur[1] if cur else None,
            "start": start, "end": time.time(),
            "attributes": attributes or None, "error": error,
        })


def activate_context(ctx: Optional[Dict[str, str]]):
    """Adopt a propagated context (worker side; reference: extract from
    the task spec before running the user function). Returns a reset
    token or None. Deliberately does NOT flip the process-global enable
    flag: once the context is reset, this worker stops tracing unless
    the next task carries a context too."""
    if not ctx:
        return None
    return _current.set((ctx["trace_id"], ctx["parent_span_id"]))


def deactivate_context(token) -> None:
    if token is not None:
        _current.reset(token)


@contextlib.contextmanager
def _maybe_otel_span(name: str, attributes: Dict):
    """Mirror to OpenTelemetry when available (reference:
    _OpenTelemetryProxy:34 — tracing works without it installed)."""
    try:
        from opentelemetry import trace as otel_trace
        tracer = otel_trace.get_tracer("ray_tpu")
    except Exception:
        yield
        return
    with tracer.start_as_current_span(name, attributes={
            k: str(v) for k, v in (attributes or {}).items()}):
        yield


# ---------------------------------------------------------------------------
# collection / export (driver side)
# ---------------------------------------------------------------------------
def get_spans() -> List[dict]:
    """All spans flushed to the GCS store plus this process's buffer."""
    flush()
    from .._private import state
    rt = state.current_or_none()
    stored = rt.gcs.spans() if rt is not None else []
    return stored


def export_chrome_trace(filename: Optional[str] = None) -> List[dict]:
    """Spans + task timeline as one Chrome-trace JSON (reference:
    `ray timeline` merged with span events)."""
    import json

    from . import state as state_api

    events = state_api.timeline()
    for s in get_spans():
        events.append({
            "cat": "span", "name": s["name"], "ph": "X",
            "ts": s["start"] * 1e6, "dur": (s["end"] - s["start"]) * 1e6,
            "pid": "spans", "tid": s["trace_id"][:8],
            "args": {k: v for k, v in s.items()
                     if k in ("trace_id", "span_id", "parent_span_id",
                              "attributes", "error")},
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
