"""Drop-in `multiprocessing.Pool` backed by ray_tpu actors.

Reference parity: python/ray/util/multiprocessing/pool.py (Pool with
apply/apply_async/map/map_async/imap/imap_unordered/starmap over Ray
actors). Each pool process is an actor, so pool workers can hold jitted
functions warm across calls — the property a TPU inference pool needs.
"""
import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

__all__ = ["Pool", "AsyncResult", "TimeoutError"]

TimeoutError = ray_tpu.exceptions.GetTimeoutError


class _PoolWorker:
    """One pool process (reference: pool.py PoolActor)."""

    def __init__(self, initializer=None, initargs=()):
        if initializer:
            initializer(*initargs)

    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(*a) for a in chunk]


class AsyncResult:
    """Reference: pool.py AsyncResult."""

    def __init__(self, refs: List, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        values = ray_tpu.get(self._refs, timeout=timeout)
        if self._single:
            return values[0]
        return list(itertools.chain.from_iterable(values))

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    """Reference: util/multiprocessing/pool.py Pool."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        if processes is None:
            processes = max(
                1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._n = processes
        cls = ray_tpu.remote(_PoolWorker)
        if ray_remote_args:
            cls = cls.options(**ray_remote_args)
        self._actors = [cls.remote(initializer, initargs)
                        for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._lock = threading.Lock()

    # -- helpers -----------------------------------------------------------
    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _next_actor(self):
        with self._lock:
            return self._actors[next(self._rr)]

    @staticmethod
    def _chunks(iterable: Iterable, chunksize: int) -> List[List]:
        out, cur = [], []
        for item in iterable:
            cur.append((item,) if not isinstance(item, tuple) else item)
            if len(cur) >= chunksize:
                out.append(cur)
                cur = []
        if cur:
            out.append(cur)
        return out

    def _map_refs(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int], star: bool) -> List:
        items = list(iterable)
        if not star:
            items = [(i,) for i in items]
        if chunksize is None:
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        return [self._next_actor().run_batch.remote(fn, chunk)
                for chunk in self._chunks(items, chunksize)]

    # -- API ---------------------------------------------------------------
    def apply(self, fn: Callable, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args=(), kwds=None) -> AsyncResult:
        self._check()
        ref = self._next_actor().run.remote(fn, tuple(args), kwds or {})
        return AsyncResult([ref], single=True)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        self._check()
        return AsyncResult(self._map_refs(fn, iterable, chunksize, False),
                           single=False)

    def starmap(self, fn, iterable, chunksize=None) -> List[Any]:
        self._check()
        return AsyncResult(self._map_refs(fn, iterable, chunksize, True),
                           single=False).get()

    def imap(self, fn, iterable, chunksize: int = 1):
        self._check()
        refs = self._map_refs(fn, iterable, chunksize, False)
        for ref in refs:
            for v in ray_tpu.get(ref):
                yield v

    def imap_unordered(self, fn, iterable, chunksize: int = 1):
        self._check()
        refs = self._map_refs(fn, iterable, chunksize, False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for v in ray_tpu.get(ready[0]):
                yield v

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
