"""ActorGroup: manage N identical actors as one unit.

Reference parity: python/ray/util/actor_group.py:62 (ActorGroup —
broadcast method calls across members, used for SPMD-style worker sets
outside of Train).
"""
from typing import Any, Callable, List, Optional

import ray_tpu

__all__ = ["ActorGroup"]


class ActorGroup:
    def __init__(self, actor_cls, num_actors: int,
                 actor_options: Optional[dict] = None,
                 init_args: tuple = (), init_kwargs: Optional[dict] = None):
        if num_actors < 1:
            raise ValueError("num_actors must be >= 1")
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        cls = actor_cls if hasattr(actor_cls, "remote") \
            else ray_tpu.remote(actor_cls)
        if actor_options:
            cls = cls.options(**actor_options)
        self._actors = [cls.remote(*init_args, **(init_kwargs or {}))
                        for _ in range(num_actors)]

    def __len__(self) -> int:
        return len(self._actors)

    @property
    def actors(self) -> List:
        return list(self._actors)

    def execute_async(self, method: str, *args, **kwargs) -> List:
        """Fan a method call to every member; returns refs."""
        return [getattr(a, method).remote(*args, **kwargs)
                for a in self._actors]

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(method, *args, **kwargs))

    def execute_single_async(self, index: int, method: str, *args, **kwargs):
        return getattr(self._actors[index], method).remote(*args, **kwargs)

    def execute_single(self, index: int, method: str, *args, **kwargs):
        return ray_tpu.get(
            self.execute_single_async(index, method, *args, **kwargs))

    def execute_with_rank(self, method: str, *args, **kwargs) -> List[Any]:
        """Like execute(), but prepends each member's rank to the args —
        the SPMD pattern (rank -> mesh coordinate)."""
        return ray_tpu.get([
            getattr(a, method).remote(rank, *args, **kwargs)
            for rank, a in enumerate(self._actors)])

    def shutdown(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []
