"""(reference: python/ray/util/xgboost/__init__.py — removed in Ray 2.0
in favor of Train's XGBoostTrainer; the parity surface is the same
redirect.)"""

raise DeprecationWarning(
    "ray_tpu.util.xgboost mirrors ray.util.xgboost, which was removed as "
    "of Ray 2.0. Use ray_tpu.train.XGBoostTrainer instead."
)
