"""joblib backend running jobs as ray_tpu tasks.

Reference parity: python/ray/util/joblib/ (register_ray +
ray_backend.py RayBackend) — lets sklearn-style `with
joblib.parallel_backend("ray_tpu"):` fan cross-validation / grid-search
work out over the cluster unchanged.
"""
from typing import Any

__all__ = ["register_ray"]


def register_ray():
    """Register the 'ray_tpu' joblib backend (reference:
    util/joblib/__init__.py register_ray)."""
    from joblib.parallel import ParallelBackendBase, register_parallel_backend

    import ray_tpu

    class _Job:
        def __init__(self, ref):
            self._ref = ref

        def get(self, timeout=None):
            out = ray_tpu.get(self._ref, timeout=timeout)
            return out

    class RayTpuBackend(ParallelBackendBase):
        supports_timeout = True
        default_n_jobs = -1

        def effective_n_jobs(self, n_jobs: int) -> int:
            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs == -1:
                return cpus
            if n_jobs < 0:
                return max(1, cpus + 1 + n_jobs)
            return n_jobs

        def apply_async(self, func, callback=None) -> Any:
            @ray_tpu.remote
            def _joblib_task(f):
                return f()

            ref = _joblib_task.remote(func)
            job = _Job(ref)
            if callback is not None:
                ref.future().add_done_callback(
                    lambda fut: callback(job))
            return job

        def configure(self, n_jobs=1, parallel=None, **kwargs):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def abort_everything(self, ensure_ready=True):
            pass

    register_parallel_backend("ray_tpu", RayTpuBackend)
    # alias matching the reference's name for drop-in scripts
    register_parallel_backend("ray", RayTpuBackend)
