"""ActorPool (reference: python/ray/util/actor_pool.py ActorPool)."""

from __future__ import annotations

from typing import Any, Callable, List

from .. import api


class ActorPool:
    """Round-robins work over a fixed set of actors with a bounded number
    of in-flight submissions per actor, same contract as the reference."""

    def __init__(self, actors: List):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef (reference: ActorPool.submit)."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout=None):
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        idx = self._next_return_index
        while idx not in self._index_to_future:
            self._drain_one(timeout)
        future = self._index_to_future.pop(idx)
        self._next_return_index += 1
        self._return_actor(future)
        return api.get(future, timeout=timeout)

    def get_next_unordered(self, timeout=None):
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        while not self._index_to_future:
            self._drain_one(timeout)
        ready, _ = api.wait(
            list(self._index_to_future.values()), num_returns=1,
            timeout=timeout)
        if not ready:
            raise TimeoutError("Timed out waiting for a result")
        future = ready[0]
        for idx, f in list(self._index_to_future.items()):
            if f == future:
                del self._index_to_future[idx]
                if idx == self._next_return_index:
                    self._next_return_index += 1
                break
        self._return_actor(future)
        return api.get(future, timeout=timeout)

    def _drain_one(self, timeout):
        raise TimeoutError("Result not yet available")

    def _return_actor(self, future):
        actor = self._future_to_actor.pop(future, None)
        if actor is None:
            return
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def map(self, fn: Callable, values: List[Any]):
        """Yields results in order (reference: ActorPool.map)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: List[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
