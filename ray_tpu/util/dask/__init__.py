"""Dask-on-ray_tpu scheduler (reference: python/ray/util/dask/ —
`ray_dask_get` in scheduler.py, a custom dask scheduler executing graph
nodes as Ray tasks).

`ray_dask_get(dsk, keys)` implements dask's scheduler protocol: a dask
graph is a dict of key -> computation, where a computation is either a
literal, a key reference, or a task tuple `(callable, arg1, arg2, ...)`
(args may themselves be nested computations). Each task node becomes one
`@remote` task whose upstream args are ObjectRefs, so independent graph
branches run in parallel on the cluster and intermediates stay in the
object store. The protocol helpers (`istask`/`ishashable`) are
re-implemented locally so the scheduler itself imports nothing from dask
— `dask` is only needed by the caller that builds graphs
(`enable_dask_on_ray` gates on it).
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

from ... import api

__all__ = ["ray_dask_get", "enable_dask_on_ray", "disable_dask_on_ray"]


def _ishashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x) -> bool:
    """A dask task is a tuple whose head is callable (dask.core.istask)."""
    return isinstance(x, tuple) and bool(x) and callable(x[0])


@api.remote
def _dask_task(fn, *resolved):
    """One graph node; upstream ObjectRefs arrive resolved by the runtime."""
    return fn(*resolved)


def _materialize(comp, dsk: Dict, refs: Dict[Hashable, Any], seen: set):
    """Recursively turn a computation into a value/ref structure whose
    task nodes are submitted remote tasks."""
    if _istask(comp):
        fn, *args = comp
        # Nested computations inside args collapse to refs/literals; a
        # nested task tuple becomes its own remote task (dask nests
        # subgraphs this way rather than via extra keys).
        rargs = [_resolve_arg(a, dsk, refs, seen) for a in args]
        return _dask_task.remote(fn, *rargs)
    return _resolve_arg(comp, dsk, refs, seen)


def _resolve_arg(a, dsk, refs, seen):
    if _ishashable(a) and a in dsk:
        return _get_ref(a, dsk, refs, seen)
    if _istask(a):
        return _materialize(a, dsk, refs, seen)
    if isinstance(a, list):
        return [_resolve_arg(x, dsk, refs, seen) for x in a]
    if isinstance(a, tuple):
        return tuple(_resolve_arg(x, dsk, refs, seen) for x in a)
    if isinstance(a, dict):
        return {k: _resolve_arg(v, dsk, refs, seen) for k, v in a.items()}
    return a


def _get_ref(key, dsk, refs, seen):
    if key in refs:
        return refs[key]
    if key in seen:
        raise ValueError(f"cycle detected in dask graph at key {key!r}")
    seen.add(key)
    refs[key] = _materialize(dsk[key], dsk, refs, seen)
    return refs[key]


def ray_dask_get(dsk: Dict, keys, **kwargs):
    """Dask scheduler entry (reference: scheduler.py ray_dask_get).
    Returns computed values matching the (possibly nested) `keys`
    structure, as dask schedulers must."""
    refs: Dict[Hashable, Any] = {}
    seen: set = set()

    def deep_get(v):
        if isinstance(v, api.ObjectRef):
            return api.get(v)
        if isinstance(v, list):
            return [deep_get(x) for x in v]
        if isinstance(v, tuple):
            return tuple(deep_get(x) for x in v)
        if isinstance(v, dict):
            return {k: deep_get(x) for k, x in v.items()}
        return v

    def compute(k):
        if isinstance(k, list):
            return [compute(x) for x in k]
        return deep_get(_get_ref(k, dsk, refs, seen))

    return compute(keys)


# Alias matching the reference's synchronous variant.
ray_dask_get_sync = ray_dask_get


def enable_dask_on_ray(shuffle: str = "tasks"):
    """Set ray_dask_get as dask's default scheduler (requires dask).
    Usable as a context manager, like the reference."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires `dask` to be installed; "
            "ray_dask_get itself works on plain graph dicts without it."
        ) from e
    return dask.config.set(scheduler=ray_dask_get, shuffle=shuffle)


def disable_dask_on_ray():
    import dask

    return dask.config.set(scheduler=None, shuffle=None)
