"""Device profiling utilities — the TPU-native analogue of the
reference's GPU profiling hooks (nsight runtime-env plugin,
_private/runtime_env/nsight.py, and per-function hooks in
_private/profiling.py).

On TPU the profiler of record is jax.profiler: traces capture XLA
execution, HBM usage, and ICI communication, viewable in TensorBoard or
Perfetto. These helpers wrap it with the framework's session layout and
compose with remote tasks (each worker process can trace its own device
work).
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, Callable, Dict, Optional


def default_logdir() -> str:
    """Session-scoped trace dir (driver) or a /tmp fallback."""
    from .._private import state
    rt = state.current_or_none()
    base = getattr(rt, "session_dir", None) if rt is not None else None
    if base is None:
        base = "/tmp/ray_tpu_profiles"
    return os.path.join(base, "profiles")


@contextlib.contextmanager
def trace(logdir: Optional[str] = None, *, host_tracer_level: int = 2,
          create_perfetto_link: bool = False):
    """Context manager: capture a jax.profiler trace of the enclosed
    device work (reference: the nsight plugin wraps a worker in `nsys
    profile`; here the XLA profiler wraps a region).

        with profiling.trace("/tmp/tb"):
            state, _ = train_step(state, batch)
            jax.block_until_ready(state)
    """
    import jax
    logdir = logdir or default_logdir()
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def profile(fn: Optional[Callable] = None, *,
            logdir: Optional[str] = None):
    """Decorator variant of `trace` for remote task/actor methods:

        @ray_tpu.remote(num_tpus=1)
        @profiling.profile
        def step(batch): ...
    """
    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with trace(logdir):
                return f(*args, **kwargs)
        return wrapper
    return deco(fn) if fn is not None else deco


def device_memory_stats(device_index: int = 0) -> Dict[str, Any]:
    """Per-device HBM stats (reference: the dashboard's GPU memory
    reporter; TPU runtimes expose bytes_in_use/peak via
    Device.memory_stats)."""
    import jax
    devs = jax.local_devices()
    if not devs or device_index >= len(devs):
        return {}
    stats = devs[device_index].memory_stats() or {}
    return dict(stats)


def annotate(name: str):
    """Named profiler span (reference: _private/profiling.profile):
    shows up as a labeled region in the trace viewer.

        with profiling.annotate("tokenize"): ...
    """
    import jax
    return jax.profiler.TraceAnnotation(name)


class Timer:
    """Lightweight wall-clock section timer for host-side code paths
    (reference: _private/profiling.py chrome-event helpers); records into
    the GCS span store so `ray_tpu timeline` includes it."""

    def __init__(self, name: str):
        self.name = name
        self.elapsed_s: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed_s = time.perf_counter() - self._t0
        from .._private import state
        rt = state.current_or_none()
        gcs = getattr(rt, "gcs", None)
        if gcs is not None:
            gcs.record_spans([{
                "name": self.name, "cat": "profiling",
                "ts": (self._t0) * 1e6, "dur": self.elapsed_s * 1e6,
                "pid": os.getpid(), "tid": 0, "ph": "X"}])
        return False
