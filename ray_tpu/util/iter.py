"""ParallelIterator: sharded lazy iterators over actors.

Reference parity: python/ray/util/iter.py (from_items/from_iterators,
for_each, filter, batch, flatten, gather_sync, gather_async, union,
shuffle via local_shuffle, take/show; shards held by ParallelIteratorWorker
actors).
"""
import collections
import random
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

__all__ = ["ParallelIterator", "from_items", "from_iterators", "from_range"]


class _ShardWorker:
    """Holds one shard's (lazy) item source + transform chain
    (reference: util/iter.py ParallelIteratorWorker)."""

    def __init__(self, items):
        self._base = list(items)
        self._ops: List = []

    def add_op(self, kind: str, fn=None, arg=None):
        self._ops.append((kind, fn, arg))
        return True

    def _run_chain(self):
        it: Iterable = iter(self._base)
        for kind, fn, arg in self._ops:
            if kind == "for_each":
                it = map(fn, it)
            elif kind == "filter":
                it = filter(fn, it)
            elif kind == "batch":
                def _batched(source, n=arg):
                    buf = []
                    for x in source:
                        buf.append(x)
                        if len(buf) >= n:
                            yield buf
                            buf = []
                    if buf:
                        yield buf
                it = _batched(it)
            elif kind == "flatten":
                def _flat(source):
                    for x in source:
                        yield from x
                it = _flat(it)
            elif kind == "shuffle":
                items = list(it)
                random.Random(arg).shuffle(items)
                it = iter(items)
        return it

    def collect(self) -> List:
        return list(self._run_chain())

    def next_chunk(self, start: int, n: int) -> List:
        # simple paging for gather_async
        return list(self._run_chain())[start:start + n]


class ParallelIterator:
    """Reference: util/iter.py ParallelIterator."""

    def __init__(self, actors: List, name: str = "iter"):
        self._actors = actors
        self.name = name

    # -- transforms (lazy, applied on shards) ------------------------------
    def _add_op(self, kind, fn=None, arg=None, label=""):
        ray_tpu.get([a.add_op.remote(kind, fn, arg) for a in self._actors])
        return ParallelIterator(self._actors, f"{self.name}.{label}")

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._add_op("for_each", fn, label="for_each()")

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._add_op("filter", fn, label="filter()")

    def batch(self, n: int) -> "ParallelIterator":
        return self._add_op("batch", None, n, label=f"batch({n})")

    def flatten(self) -> "ParallelIterator":
        return self._add_op("flatten", label="flatten()")

    def local_shuffle(self, shuffle_buffer_size: int = 0,
                      seed: Optional[int] = None) -> "ParallelIterator":
        return self._add_op("shuffle", None, seed, label="shuffle()")

    # -- gather ------------------------------------------------------------
    def num_shards(self) -> int:
        return len(self._actors)

    def gather_sync(self) -> "LocalIterator":
        """Round-robin over shards, in order (reference:
        iter.py gather_sync)."""
        shards = ray_tpu.get([a.collect.remote() for a in self._actors])
        queues = [collections.deque(s) for s in shards]

        def _gen():
            while any(queues):
                for q in queues:
                    if q:
                        yield q.popleft()
        return LocalIterator(_gen)

    def gather_async(self) -> "LocalIterator":
        """Completion order (reference: iter.py gather_async)."""
        refs = {a.collect.remote(): i for i, a in enumerate(self._actors)}

        def _gen():
            pending = list(refs.keys())
            while pending:
                ready, pending = ray_tpu.wait(pending, num_returns=1)
                for item in ray_tpu.get(ready[0]):
                    yield item
        return LocalIterator(_gen)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        return ParallelIterator(self._actors + other._actors,
                                f"{self.name}+{other.name}")

    def take(self, n: int) -> List:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def show(self, n: int = 20):
        for x in self.take(n):
            print(x)

    def __iter__(self):
        return iter(self.gather_sync())


class LocalIterator:
    def __init__(self, gen_factory):
        self._factory = gen_factory

    def __iter__(self):
        return iter(self._factory())


def from_items(items: List[Any], num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    shards = [items[i::num_shards] for i in range(num_shards)]
    Worker = ray_tpu.remote(_ShardWorker)
    actors = [Worker.remote(s) for s in shards]
    return ParallelIterator(actors, f"from_items[{len(items)}]")


def from_iterators(generators: List[Iterable],
                   repeat: bool = False) -> ParallelIterator:
    Worker = ray_tpu.remote(_ShardWorker)
    actors = [Worker.remote(list(g)) for g in generators]
    return ParallelIterator(actors, f"from_iterators[{len(generators)}]")


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)
