"""Placement groups: gang reservation of resource bundles.

Reference parity: python/ray/util/placement_group.py (placement_group()
:145, PlacementGroup handle :41, remove/get/table helpers) over the
GCS-side manager (gcs_placement_group_manager.cc). The TPU-era point of a
placement group is *slice gang scheduling*: reserve all hosts/chips of a
pod slice atomically so an SPMD mesh program can launch across them
(SURVEY.md §7 Phase 1); the bundle-reservation scheme is formatted group
resources, see _private/placement.py.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional

from .. import api as _api
from .._private import state as _state
from .._private.placement import (  # noqa: F401  (re-exported strategies)
    PACK, SPREAD, STRICT_PACK, STRICT_SPREAD, rewrite_demand_for_pg)


class PlacementGroup:
    """Handle to a placement group (reference: util/placement_group.py:41)."""

    def __init__(self, id: str, bundles: Optional[List[Dict[str, float]]] = None):
        self.id = id
        self._bundles = bundles
        self._lock = threading.Lock()

    @staticmethod
    def empty() -> "PlacementGroup":
        return PlacementGroup("")

    @property
    def is_empty(self) -> bool:
        return not self.id

    def _fetch_bundles(self) -> List[Dict[str, float]]:
        with self._lock:
            if self._bundles is None:
                table = _state.current().gcs_request("pg_table")
                info = table.get(self.id)
                if info is None:
                    raise ValueError(f"Unknown placement group {self.id}")
                self._bundles = [info["bundles"][i]
                                 for i in sorted(info["bundles"])]
            return self._bundles

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self._fetch_bundles()

    @property
    def bundle_count(self) -> int:
        return len(self._fetch_bundles())

    def ready(self) -> "_api.ObjectRef":
        """ObjectRef resolving to True when all bundles are reserved; use
        ``ray_tpu.get(pg.ready(), timeout=...)`` (reference semantics)."""
        rt = _state.current()
        if hasattr(rt, "placement_group_ready_ref"):
            return _api.ObjectRef(rt.placement_group_ready_ref(self.id))
        # Worker context: readiness via a zero-resource probe task on the
        # driver (the gcs_request wait runs on the driver's handler pool).
        pg_id = self.id

        @_api.remote
        def _pg_ready() -> bool:
            return _state.current().gcs_request(
                "pg_wait_ready", pg_id_hex=pg_id, timeout=None)

        return _pg_ready.options(num_cpus=0).remote()

    def wait(self, timeout_seconds: float = 30) -> bool:
        """Block until ready; False on timeout (reference:
        PlacementGroup.wait)."""
        try:
            return bool(_state.current().gcs_request(
                "pg_wait_ready", pg_id_hex=self.id,
                timeout=timeout_seconds))
        except Exception:
            raise

    def __eq__(self, other):
        return isinstance(other, PlacementGroup) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))

    def __repr__(self):
        return f"PlacementGroup(id={self.id[:16]})"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "",
                    lifetime: Optional[str] = None,
                    _max_cpu_fraction_per_node: Optional[float] = None
                    ) -> PlacementGroup:
    """Create a placement group (reference: util/placement_group.py:145).

    Returns immediately; reservation is asynchronous. Use ``pg.ready()`` /
    ``pg.wait()`` to block on it.
    """
    if not _state.is_initialized():
        _api.init(ignore_reinit_error=True)
    pg_id = uuid.uuid4().hex
    bundles = [dict(b) for b in bundles]
    _state.current().gcs_request(
        "pg_create", pg_id_hex=pg_id, bundles=bundles, strategy=strategy,
        name=name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release all bundles (reference: util/placement_group.py
    remove_placement_group). Running tasks keep their workers until they
    finish; no new tasks can target the group."""
    _state.current().gcs_request("pg_remove", pg_id_hex=pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    info = _state.current().gcs_request("pg_get_by_name", name=name)
    if info is None:
        raise ValueError(f"Failed to look up placement group '{name}'")
    return PlacementGroup(info["pg_id_hex"], info["bundles"])


def placement_group_table(pg: Optional[PlacementGroup] = None) -> dict:
    table = _state.current().gcs_request("pg_table")
    if pg is not None:
        return table.get(pg.id, {})
    return table


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The placement group of the currently executing task/actor, if any
    (reference: util/placement_group.py get_current_placement_group)."""
    from .._private import worker_proc
    spec = worker_proc.current_task_spec()
    if spec is None or not getattr(spec, "placement_group_id", None):
        return None
    return PlacementGroup(spec.placement_group_id.decode()
                          if isinstance(spec.placement_group_id, bytes)
                          else spec.placement_group_id)


def check_placement_group_index(pg: PlacementGroup, bundle_index: int):
    if bundle_index >= pg.bundle_count or bundle_index < -1:
        raise ValueError(
            f"placement_group_bundle_index must be -1 or in "
            f"[0, {pg.bundle_count}), got {bundle_index}")
