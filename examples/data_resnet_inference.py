"""ResNet-50 batch inference through Data actor pools (reference config
#3: Ray Data `map_batches` ResNet-50 over ImageNet — the
`map_batches(..., num_gpus=1)` GPU path, actor_pool_map_operator.py:34).

Synthetic ImageNet-shaped images (zero egress); each pool actor holds a
jitted ResNet-50 (`num_tpus=1` pins a chip per actor on TPU hosts). Run:

    python examples/data_resnet_inference.py [--images 256] [--smoke]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from examples._common import respect_jax_platform_env  # noqa: E402


class ResNetPredictor:
    def __init__(self, tiny: bool):
        from ray_tpu.models import ResNetConfig, make_predictor

        cfg = ResNetConfig.tiny() if tiny else ResNetConfig.resnet50()
        self.predict = make_predictor(cfg)

    def __call__(self, batch):
        import numpy as np

        batch["label"] = np.asarray(self.predict(batch["image"]))
        return batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-tpus", type=float, default=0)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    respect_jax_platform_env()
    if args.smoke:
        args.images, args.image_size = 64, 64

    import numpy as np

    import ray_tpu
    import ray_tpu.data as rd

    ray_tpu.init(ignore_reinit_error=True)
    rng = np.random.default_rng(0)
    side = args.image_size
    ds = rd.from_items([
        {"image": rng.normal(size=(side, side, 3)).astype(np.float32)}
        for _ in range(args.images)])

    kwargs = dict(batch_size=args.batch_size,
                  concurrency=args.concurrency,
                  fn_constructor_args=(args.smoke,))
    if args.num_tpus:
        kwargs["num_tpus"] = args.num_tpus
    t0 = time.perf_counter()
    out = ds.map_batches(ResNetPredictor, **kwargs)
    n = sum(1 for _ in out.iter_rows())
    dt = time.perf_counter() - t0
    print(json.dumps({
        "workload": "data_resnet_inference", "images": n,
        "images_per_s": round(n / dt, 2),
        "batch_size": args.batch_size,
        "concurrency": args.concurrency,
    }))


if __name__ == "__main__":
    main()
