"""Collective microbenchmark (reference config #2: the
`ray.util.collective` allreduce/allgather suite whose committed numbers
are bus-bandwidth GB/s over NCCL — BASELINE.md north-star row).

Here the backend is XLA over a device mesh: allreduce lowers to psum
over ICI on real TPU slices (CPU ring on the test backend). Bus
bandwidth uses the standard 2(n-1)/n allreduce traffic model. Run:

    python examples/collective_microbench.py [--size-mb 64] [--iters 10]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from examples._common import respect_jax_platform_env  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    respect_jax_platform_env()
    if args.smoke:
        args.size_mb, args.iters = 4.0, 3

    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as Ps

    # The intra-host/slice data plane: psum/all_gather over the local
    # device mesh — the ICI path the reference reaches via NCCL. (The
    # ray_tpu.util.collective API layers process-group semantics on the
    # same lowering for multi-host actor groups.)
    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("world",))
    elems = max(n, int(args.size_mb * 1e6 / 4) // n * n)
    x = jax.device_put(jnp.ones((elems,), jnp.float32),
                       NamedSharding(mesh, Ps("world")))

    allreduce = jax.jit(shard_map(
        functools.partial(jax.lax.psum, axis_name="world"),
        mesh=mesh, in_specs=Ps("world"), out_specs=Ps("world")))
    gather_fn = functools.partial(jax.lax.all_gather, axis_name="world",
                                  tiled=True)
    try:
        # all_gather's replicated output needs the replication check off
        # (kwarg renamed across jax versions).
        allgather = jax.jit(shard_map(
            gather_fn, mesh=mesh, in_specs=Ps("world"), out_specs=Ps(),
            check_vma=False))
    except TypeError:
        allgather = jax.jit(shard_map(
            gather_fn, mesh=mesh, in_specs=Ps("world"), out_specs=Ps(),
            check_rep=False))

    jax.block_until_ready(allreduce(x))  # compile
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = allreduce(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / args.iters
    nbytes = elems * 4
    # NCCL-convention bus bandwidth: algbw * 2(n-1)/n
    algbw = nbytes / dt / 1e9
    busbw = algbw * (2 * (n - 1) / n if n > 1 else 1.0)

    jax.block_until_ready(allgather(x))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = allgather(x)
    jax.block_until_ready(out)
    ag_dt = (time.perf_counter() - t0) / args.iters
    ag_busbw = (nbytes * (n - 1) / max(n, 1)) / ag_dt / 1e9

    print(json.dumps({
        "workload": "collective_microbench", "devices": n,
        "size_mb": args.size_mb,
        "allreduce_ms": round(dt * 1e3, 3),
        "allreduce_busbw_gbps": round(busbw, 2),
        "allgather_ms": round(ag_dt * 1e3, 3),
        "allgather_busbw_gbps": round(ag_busbw, 2),
    }))


if __name__ == "__main__":
    main()
