"""GPT-2-small fine-tune (reference config #4: Ray Train HF
TransformersTrainer GPT-2 fine-tune, release/ml_user_tests/ — the
BASELINE.md north-star tokens/sec workload).

Native GPT-2 124M-equivalent (models.GPTConfig.gpt2_small: bf16 matmuls,
flash-attention Pallas kernel, remat) trained on synthetic token streams
through JaxTrainer. Run:

    python examples/train_gpt2_finetune.py [--steps 20] [--smoke]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from examples._common import respect_jax_platform_env  # noqa: E402


def train_loop(config):
    import jax
    import numpy as np

    from ray_tpu import train
    from ray_tpu.models import GPTConfig, make_train_step

    cfg = GPTConfig.tiny() if config["smoke"] else GPTConfig.gpt2_small()
    init_state, step = make_train_step(cfg)
    state = init_state(jax.random.PRNGKey(train.get_world_rank()))
    rng = np.random.default_rng(train.get_world_rank())
    B, S = config["batch_size"], config["seq_len"]
    if config["smoke"]:
        S = min(S, cfg.max_seq_len)

    # compile step excluded from timing
    toks = rng.integers(0, cfg.vocab_size, (B, S), dtype=np.int32)
    state, _ = step(state, (toks, np.roll(toks, -1, 1)))
    jax.block_until_ready(state["params"])

    t0 = time.perf_counter()
    tokens_done = 0
    for i in range(config["steps"]):
        toks = rng.integers(0, cfg.vocab_size, (B, S), dtype=np.int32)
        state, metrics = step(state, (toks, np.roll(toks, -1, 1)))
        tokens_done += B * S
    jax.block_until_ready(state["params"])
    dt = time.perf_counter() - t0
    train.report({
        "loss": float(metrics["loss"]),
        "tokens_per_s": tokens_done / dt,
        "step_ms": dt / config["steps"] * 1e3,
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--use-tpu", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    respect_jax_platform_env()
    if args.smoke:
        args.steps, args.batch_size, args.seq_len = 3, 2, 64

    import ray_tpu
    from ray_tpu.train import JaxTrainer, ScalingConfig

    ray_tpu.init(ignore_reinit_error=True)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"steps": args.steps,
                           "batch_size": args.batch_size,
                           "seq_len": args.seq_len,
                           "smoke": args.smoke},
        scaling_config=ScalingConfig(num_workers=args.workers,
                                     use_tpu=args.use_tpu))
    result = trainer.fit()
    if result.error is not None:
        print(json.dumps({"workload": "train_gpt2_finetune",
                          "error": str(result.error)}))
        raise SystemExit(1)
    print(json.dumps({"workload": "train_gpt2_finetune",
                      **{k: round(float(v), 3)
                         for k, v in result.metrics.items()
                         if k in ("loss", "tokens_per_s", "step_ms")}}))


if __name__ == "__main__":
    main()
