"""Data-parallel MNIST training (reference config #1: TorchTrainer MNIST,
python/ray/train/examples/pytorch/ run with 2 CPU workers).

JaxTrainer runs `train_loop_per_worker` on N workers; each worker builds
the same MLP, shards the (synthetic, zero-egress) MNIST-shaped dataset via
streaming_split, and reports loss/accuracy per epoch. Run:

    python examples/train_mnist.py [--workers 2] [--epochs 2] [--smoke]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from examples._common import respect_jax_platform_env  # noqa: E402


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu import train

    rng = jax.random.PRNGKey(train.get_world_rank())

    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (784, 128)) * 0.05,
            "b1": jnp.zeros(128),
            "w2": jax.random.normal(k2, (128, 10)) * 0.05,
            "b2": jnp.zeros(10),
        }

    def loss_fn(params, x, y):
        h = jax.nn.relu(x @ params["w1"] + params["b1"])
        logits = h @ params["w2"] + params["b2"]
        onehot = jax.nn.one_hot(y, 10)
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * onehot, axis=-1))
        acc = jnp.mean(jnp.argmax(logits, -1) == y)
        return loss, acc

    tx = optax.adam(config["lr"])
    params = init_params(rng)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    shard = train.get_dataset_shard("train")
    for epoch in range(config["epochs"]):
        n, loss_sum, acc_sum, batches = 0, 0.0, 0.0, 0
        t0 = time.perf_counter()
        for batch in shard.iter_batches(batch_size=config["batch_size"]):
            x = jnp.asarray(batch["image"]).reshape(-1, 784)
            y = jnp.asarray(batch["label"])
            params, opt_state, loss, acc = step(params, opt_state, x, y)
            n += len(y)
            loss_sum += float(loss)
            acc_sum += float(acc)
            batches += 1
        train.report({
            "epoch": epoch, "loss": loss_sum / max(batches, 1),
            "accuracy": acc_sum / max(batches, 1),
            "samples_per_s": n / (time.perf_counter() - t0),
        })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    respect_jax_platform_env()
    if args.smoke:
        args.rows, args.epochs = 1024, 1

    import numpy as np

    import ray_tpu
    import ray_tpu.data as rd
    from ray_tpu.train import JaxTrainer, ScalingConfig

    ray_tpu.init(ignore_reinit_error=True)
    # A gang larger than the cluster can never schedule (each worker
    # reserves one CPU) — size to what's there, like the reference's
    # ScalingConfig guidance.
    workers = min(args.workers,
                  max(1, int(ray_tpu.cluster_resources().get("CPU", 1))))
    rng = np.random.default_rng(0)
    ds = rd.from_items([
        {"image": rng.normal(size=(28, 28)).astype(np.float32),
         "label": int(rng.integers(0, 10))}
        for _ in range(args.rows)])

    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"lr": 1e-3, "epochs": args.epochs,
                           "batch_size": args.batch_size},
        scaling_config=ScalingConfig(num_workers=workers),
        datasets={"train": ds})
    result = trainer.fit()
    if result.error is not None:
        print(json.dumps({"workload": "train_mnist",
                          "error": str(result.error)}))
        raise SystemExit(1)
    print(json.dumps({"workload": "train_mnist", "workers": workers,
                      **{k: round(float(result.metrics[k]), 4)
                         for k in ("loss", "accuracy", "samples_per_s")
                         if k in result.metrics}}))


if __name__ == "__main__":
    main()
