"""RLlib PPO (reference config #5: rllib/tuned_examples/ppo/ — the
multi-learner PPO suite; here: mesh-DP JAX learner + env-runner actors).

Run:

    python examples/rllib_ppo.py [--iters 5] [--smoke]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from examples._common import respect_jax_platform_env  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--env", default="CartPole-v1")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--runners", type=int, default=2)
    ap.add_argument("--fragment", type=int, default=512)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    respect_jax_platform_env()
    if args.smoke:
        args.iters, args.fragment = 2, 128

    import ray_tpu
    from ray_tpu.rllib import PPOConfig

    ray_tpu.init(ignore_reinit_error=True)
    algo = (PPOConfig()
            .environment(args.env)
            .env_runners(num_env_runners=args.runners,
                         rollout_fragment_length=args.fragment)
            .training(lr=3e-4)
            .debugging(seed=0)
            .build())
    result = {}
    try:
        for _ in range(args.iters):
            result = algo.train()
    finally:
        algo.stop()
    print(json.dumps({
        "workload": "rllib_ppo", "env": args.env,
        "iterations": result.get("training_iteration"),
        "episode_return_mean": round(
            float(result.get("episode_return_mean", float("nan"))), 2),
        "env_steps": result.get("num_env_steps_sampled_lifetime"),
    }))


if __name__ == "__main__":
    main()
