"""Continuous-batching LLM serving demo.

Starts a Serve app whose replica hosts ONE shared
ContinuousBatchingEngine: concurrent requests decode together in a
slot-reuse KV batch, and a late request joins the RUNNING decode
instead of queueing behind it (vLLM-style continuous batching,
re-expressed for XLA's compile-once model — static shapes, slot reuse,
no recompiles as requests come and go).

Smoke (CPU): python examples/llm_serve_continuous.py --smoke
TPU:         python examples/llm_serve_continuous.py  (pins a chip per
             replica via num_tpus=1)
"""
import argparse
import json
import threading
import time
import urllib.request

from _common import respect_jax_platform_env


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model on CPU")
    ap.add_argument("--streams", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=32)
    args = ap.parse_args()

    if args.smoke:
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    respect_jax_platform_env()
    import jax

    import ray_tpu
    ray_tpu.init(ignore_reinit_error=True)

    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app
    from ray_tpu.models import GPTConfig, gpt_init

    if args.smoke:
        cfg = GPTConfig(vocab_size=272, d_model=64, n_heads=4,
                        n_layers=2, d_ff=128, max_seq_len=256)
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        app = build_llm_app(cfg=cfg, params=params,
                            continuous_batching=True,
                            max_batch=args.streams)
    else:
        app = build_llm_app(continuous_batching=True,
                            max_batch=args.streams, num_tpus=1)

    serve.start()
    serve.run(app, name="llm", route_prefix="/llm")
    addr = serve.proxy_address()
    print(f"serving at {addr}/llm (continuous batching, "
          f"{args.streams} slots)")

    prompts = [f"request {i}: tell me something" for i in
               range(args.streams)]
    outs = [None] * len(prompts)

    def hit(i):
        body = json.dumps({"prompt": prompts[i],
                           "max_tokens": args.max_tokens}).encode()
        r = urllib.request.urlopen(f"{addr}/llm", data=body,
                                   timeout=600)
        outs[i] = json.loads(r.read())["text"]

    t0 = time.perf_counter()
    threads = [threading.Thread(target=hit, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    n_tok = sum(len(o or "") for o in outs)
    print(f"{len(prompts)} concurrent streams x {args.max_tokens} "
          f"tokens in {dt:.2f}s (~{n_tok / dt:.0f} chars/s aggregate)")
    for p, o in zip(prompts[:2], outs[:2]):
        print(f"  {p!r} -> {o[:40]!r}...")
    serve.shutdown()


if __name__ == "__main__":
    main()
