"""Shared example plumbing."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def respect_jax_platform_env():
    """Pin jax to $JAX_PLATFORMS when set to cpu — images whose
    sitecustomize force-registers a TPU plugin override the env var, so
    the pin must go through jax.config before backend init."""
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
