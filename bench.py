"""Benchmark entry: prints ONE JSON line.

Headline metric: single-client sync task throughput, the reference's core
microbenchmark ("single client tasks sync", 1,013.2/s committed CI result,
BASELINE.md / release/perf_metrics/microbenchmark.json, suite defined in
python/ray/_private/ray_perf.py:174-189). Extras carry the wider suite:
async task throughput, actor call rates, put/get, and — when a TPU is
attached — flagship GPT train-step tokens/s.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TASKS_SYNC = 1013.2  # reference microbenchmark.json


def bench_core(extras):
    import ray_tpu

    ray_tpu.init(num_cpus=min(os.cpu_count() or 4, 16))

    @ray_tpu.remote
    def nop():
        return None

    @ray_tpu.remote
    class NopActor:
        def nop(self):
            return None

    # warmup: spin up workers, cache functions
    ray_tpu.get([nop.remote() for _ in range(100)])

    def best_of(reps, fn):
        """Best-of-N like the reference's microbenchmark harness: on a
        shared machine one rep can eat a scheduling hiccup."""
        return max(fn() for _ in range(reps))

    # single client tasks sync (ray_perf.py:174 pattern)
    def _sync():
        n = 1000
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(nop.remote())
        return n / (time.perf_counter() - t0)
    sync_rate = best_of(2, _sync)

    # single client tasks async: submit all, get all (ray_perf.py:181)
    def _async():
        n = 5000
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return n / (time.perf_counter() - t0)
    async_rate = best_of(2, _async)

    # 1:1 actor calls sync / async (ray_perf.py:196-232)
    actor = NopActor.remote()
    ray_tpu.get(actor.nop.remote())
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(actor.nop.remote())
    actor_sync = n / (time.perf_counter() - t0)
    n = 5000
    t0 = time.perf_counter()
    ray_tpu.get([actor.nop.remote() for _ in range(n)])
    actor_async = n / (time.perf_counter() - t0)

    # put/get small + put gigabytes (ray_perf.py:120-146)
    import numpy as np
    small = np.zeros(1000, dtype=np.float64)
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(small))
    put_get_rate = n / (time.perf_counter() - t0)

    big = np.zeros((1 << 28,), dtype=np.uint8)  # 256 MB
    ref = ray_tpu.put(big)  # warmup: fault in source pages, prime tmpfs
    del ref
    t0 = time.perf_counter()
    iters = 4
    for _ in range(iters):
        ref = ray_tpu.put(big)
        del ref
    put_gbps = iters * big.nbytes / (time.perf_counter() - t0) / 1e9

    # compiled DAG round trip (reference microbench: compiled DAG vs
    # task-per-call; dag/compiled_dag_node.py)
    @ray_tpu.remote
    class _Echo:
        def step(self, x):
            return x

    from ray_tpu.dag import InputNode
    e = _Echo.remote()
    with InputNode() as inp:
        dag = e.step.bind(inp)
    compiled = dag.experimental_compile()
    compiled.execute(0).get()
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        compiled.execute(i).get()
    adag_rate = n / (time.perf_counter() - t0)
    compiled.teardown()

    # placement group create+remove (reference: 749/s committed)
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        pg = placement_group([{"CPU": 1}])
        ray_tpu.get(pg.ready())
        remove_placement_group(pg)
    pg_rate = n / (time.perf_counter() - t0)

    ray_tpu.shutdown()
    extras.update({
        "compiled_dag_calls_per_s": round(adag_rate, 1),
        "pg_create_remove_per_s": round(pg_rate, 1),
        "baseline_pg_create_remove_per_s": 749.0,
        "tasks_async_per_s": round(async_rate, 1),
        "actor_calls_sync_per_s": round(actor_sync, 1),
        "actor_calls_async_per_s": round(actor_async, 1),
        "put_get_per_s": round(put_get_rate, 1),
        "put_gb_per_s": round(put_gbps, 2),
        "baseline_tasks_async_per_s": 8032.4,
        "baseline_actor_sync_per_s": 1985.8,
        "baseline_put_gb_per_s": 18.52,
    })
    return sync_rate


def bench_tpu(extras):
    try:
        import jax
        if jax.devices()[0].platform != "tpu":
            return
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import GPTConfig, make_train_step

        cfg = GPTConfig(vocab_size=32000, d_model=512, n_heads=8,
                        n_layers=8, d_ff=2048, max_seq_len=1024)
        init_state, train_step = make_train_step(cfg)
        state = init_state(jax.random.PRNGKey(0))
        # B=8 starves the MXU (measured ~12M tok/s vs ~68M at B=32 on
        # one chip); 32 keeps headroom vs HBM under tunnel sharing.
        B, S = 32, 1024
        tokens = np.random.randint(0, cfg.vocab_size, (B, S),
                                   dtype=np.int32)
        batch = (jnp.asarray(tokens), jnp.asarray(np.roll(tokens, -1, 1)))
        state, _ = train_step(state, batch)  # compile
        jax.block_until_ready(state)
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = train_step(state, batch)
        jax.block_until_ready(state)
        dt = (time.perf_counter() - t0) / iters
        extras["tpu_train_tokens_per_s"] = round(B * S / dt, 1)
        extras["tpu_train_step_ms"] = round(dt * 1e3, 2)
        extras["tpu_model"] = "gpt-42M-bf16"
    except Exception as e:  # TPU benches are best-effort
        extras["tpu_error"] = f"{type(e).__name__}: {e}"


def main():
    extras = {}
    sync_rate = bench_core(extras)
    bench_tpu(extras)
    print(json.dumps({
        "metric": "tasks_per_second_sync",
        "value": round(sync_rate, 1),
        "unit": "tasks/s",
        "vs_baseline": round(sync_rate / BASELINE_TASKS_SYNC, 3),
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
