"""Benchmark entry: prints ONE JSON line.

Headline metric: single-client sync task throughput, the reference's core
microbenchmark ("single client tasks sync", 1,013.2/s committed CI result,
BASELINE.md / release/perf_metrics/microbenchmark.json, suite defined in
python/ray/_private/ray_perf.py:174-189). Extras carry the wider suite:
async task throughput, actor call rates, put/get, and — when a TPU is
attached — flagship GPT train-step tokens/s.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_TASKS_SYNC = 1013.2  # reference microbenchmark.json

_T0 = time.monotonic()
# Total wall budget: optional (expensive-compile) sections are skipped
# once the REMAINING time can't cover their own cost, bounding overshoot
# (the always-on GPT section reserves its compile via the gates below).
try:
    # 1800 s default: the r5 envelope's REAL 1M-queued run (~175-350 s)
    # and >=32 GiB put+get (~10-16 s/GiB measured at scale on this
    # box's thin-provisioned page allocator) need the headroom; every
    # expensive section remains individually budget-gated, so a tighter
    # external budget still produces a complete (smaller-scale) result
    # line.
    _BUDGET_S = float(os.environ.get("RAY_TPU_BENCH_BUDGET_S", "1800"))
except (TypeError, ValueError):
    _BUDGET_S = 1800.0


def _budget_left() -> float:
    return _BUDGET_S - (time.monotonic() - _T0)


def bench_core(extras):
    import ray_tpu

    ray_tpu.init(num_cpus=min(os.cpu_count() or 4, 16))
    # Which store served the put numbers (arena vs file fallback) —
    # the two differ 2-3x in put bandwidth.
    from ray_tpu._private import state as _state
    extras["store_backend"] = type(_state.current().store).__name__
    from ray_tpu import _native as _nat
    extras["native_dispatch"] = bool(
        _nat.available()
        and os.environ.get("RAY_TPU_NATIVE_DISPATCH", "1") != "0")

    @ray_tpu.remote
    def nop():
        return None

    @ray_tpu.remote
    class NopActor:
        def nop(self):
            return None

    # warmup: spin up workers, cache functions
    ray_tpu.get([nop.remote() for _ in range(100)])

    def best_of(reps, fn, key=None):
        """Best-of-N like the reference's microbenchmark harness: on a
        shared machine one rep can eat a scheduling hiccup. With `key`,
        the per-rep spread (min/median/max) lands in extras — this box
        swings ~1.7x between same-state runs (PR 2 caveat), so a bare
        best-of number is not comparable across rounds without it."""
        vals = sorted(fn() for _ in range(reps))
        if key is not None:
            extras[f"spread_{key}"] = [
                round(vals[0], 1), round(statistics.median(vals), 1),
                round(vals[-1], 1)]
        return vals[-1]

    # single client tasks sync (ray_perf.py:174 pattern)
    def _sync():
        n = 1000
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(nop.remote())
        return n / (time.perf_counter() - t0)
    sync_rate = best_of(2, _sync, key="tasks_sync")

    # single client tasks async: submit all, get all (ray_perf.py:181)
    def _async():
        n = 5000
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return n / (time.perf_counter() - t0)
    async_rate = best_of(2, _async, key="tasks_async")

    # 1:1 actor calls sync / async (ray_perf.py:196-232)
    actor = NopActor.remote()
    ray_tpu.get(actor.nop.remote())
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(actor.nop.remote())
    actor_sync = n / (time.perf_counter() - t0)
    n = 5000
    t0 = time.perf_counter()
    ray_tpu.get([actor.nop.remote() for _ in range(n)])
    actor_async = n / (time.perf_counter() - t0)

    # put/get small + put gigabytes (ray_perf.py:120-146)
    import numpy as np
    small = np.zeros(1000, dtype=np.float64)
    n = 1000
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ray_tpu.put(small))
    put_get_rate = n / (time.perf_counter() - t0)

    big = np.zeros((1 << 28,), dtype=np.uint8)  # 256 MB
    ref = ray_tpu.put(big)  # warmup: fault in source pages, prime tmpfs
    del ref
    t0 = time.perf_counter()
    iters = 4
    for _ in range(iters):
        ref = ray_tpu.put(big)
        del ref
    put_gbps = iters * big.nbytes / (time.perf_counter() - t0) / 1e9

    # -- multi-client rows (ray_perf.py:113-146,185-189 pattern: the
    # reference's "multi client" is WORKERS/ACTORS acting as clients —
    # nested puts and nested submission, not extra driver processes).
    @ray_tpu.remote
    def do_put_small():
        for _ in range(100):
            ray_tpu.put(0)

    def _mc_put():
        n_tasks = 10
        t0 = time.perf_counter()
        ray_tpu.get([do_put_small.remote() for _ in range(n_tasks)])
        return n_tasks * 100 / (time.perf_counter() - t0)
    mc_put_rate = best_of(2, _mc_put, key="mc_put")

    @ray_tpu.remote
    def do_put_big():
        for _ in range(4):
            ray_tpu.put(np.zeros(10 * 1024 * 1024, dtype=np.int64))

    def _mc_put_gb():
        n_tasks = 4
        t0 = time.perf_counter()
        ray_tpu.get([do_put_big.remote() for _ in range(n_tasks)])
        per_put = 10 * 1024 * 1024 * 8  # np.zeros(10Mi, int64).nbytes
        return n_tasks * 4 * per_put / (time.perf_counter() - t0) / 1e9
    mc_put_gbps = best_of(2, _mc_put_gb, key="mc_put_gb")

    @ray_tpu.remote
    class Submitter:
        def batch(self, n):
            ray_tpu.get([nop.remote() for _ in range(n)])
            return n

    subs = [Submitter.remote() for _ in range(4)]
    ray_tpu.get([s.batch.remote(10) for s in subs])  # warm

    def _mc_tasks():
        per = 500
        t0 = time.perf_counter()
        ray_tpu.get([s.batch.remote(per) for s in subs])
        return len(subs) * per / (time.perf_counter() - t0)
    mc_tasks_rate = best_of(2, _mc_tasks, key="mc_tasks")

    # n:n actor calls async (ray_perf "n:n actor calls async"):
    # m caller actors each async-calling a distinct callee actor.
    @ray_tpu.remote
    class Caller:
        def __init__(self, callee):
            self.callee = callee

        def drive(self, n):
            ray_tpu.get([self.callee.nop.remote() for _ in range(n)])
            return n

    callees = [NopActor.remote() for _ in range(4)]
    callers = [Caller.remote(c) for c in callees]
    ray_tpu.get([c.drive.remote(10) for c in callers])  # warm

    def _nn_actor():
        per = 500
        t0 = time.perf_counter()
        ray_tpu.get([c.drive.remote(per) for c in callers])
        return len(callers) * per / (time.perf_counter() - t0)
    nn_actor_rate = best_of(2, _nn_actor, key="nn_actor")

    # streaming generators, caller-observed items/s: a worker caller
    # consumes channel streams (GEN_ITEM frames ride the direct channel
    # caller<-callee; the head hears ONE terminal accounting entry per
    # stream). The headpath row is the driver consuming the same
    # generator through the head-registered GEN_ITEM path — the new
    # channel transport should meet or beat it.
    @ray_tpu.remote
    class GenActor:
        def stream(self, n):
            for i in range(n):
                yield i

    @ray_tpu.remote
    class StreamConsumer:
        def __init__(self, g):
            self.g = g

        def consume(self, n):
            got = 0
            for _ref in self.g.stream.options(
                    num_returns="streaming").remote(n):
                got += 1
            return got

    gen_a = GenActor.remote()
    cons = StreamConsumer.remote(gen_a)
    ray_tpu.get(cons.consume.remote(50))  # warm: channel established

    def _stream_items():
        per = 1000
        t0 = time.perf_counter()
        assert ray_tpu.get(cons.consume.remote(per)) == per
        return per / (time.perf_counter() - t0)
    stream_rate = best_of(2, _stream_items, key="streaming_gen")

    def _stream_items_head():
        per = 1000
        t0 = time.perf_counter()
        got = sum(1 for _ref in gen_a.stream.options(
            num_returns="streaming").remote(per))
        assert got == per
        return per / (time.perf_counter() - t0)
    stream_head_rate = best_of(2, _stream_items_head,
                               key="streaming_gen_head")

    for a in subs + callers + callees + [gen_a, cons]:
        ray_tpu.kill(a)

    # compiled DAG round trip (reference microbench: compiled DAG vs
    # task-per-call; dag/compiled_dag_node.py)
    @ray_tpu.remote
    class _Echo:
        def step(self, x):
            return x

    from ray_tpu.dag import InputNode
    e = _Echo.remote()
    with InputNode() as inp:
        dag = e.step.bind(inp)
    compiled = dag.experimental_compile()
    compiled.execute(0).get()
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        compiled.execute(i).get()
    adag_rate = n / (time.perf_counter() - t0)
    compiled.teardown()

    # placement group create+remove (reference: 749/s committed)
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        pg = placement_group([{"CPU": 1}])
        ray_tpu.get(pg.ready())
        remove_placement_group(pg)
    pg_rate = n / (time.perf_counter() - t0)

    ray_tpu.shutdown()
    extras.update({
        "compiled_dag_calls_per_s": round(adag_rate, 1),
        "pg_create_remove_per_s": round(pg_rate, 1),
        "baseline_pg_create_remove_per_s": 749.0,
        "tasks_async_per_s": round(async_rate, 1),
        "actor_calls_sync_per_s": round(actor_sync, 1),
        "actor_calls_async_per_s": round(actor_async, 1),
        "put_get_per_s": round(put_get_rate, 1),
        "put_gb_per_s": round(put_gbps, 2),
        "multi_client_put_per_s": round(mc_put_rate, 1),
        "multi_client_put_gb_per_s": round(mc_put_gbps, 2),
        "multi_client_tasks_async_per_s": round(mc_tasks_rate, 1),
        "nn_actor_calls_async_per_s": round(nn_actor_rate, 1),
        "streaming_gen_items_per_s": round(stream_rate, 1),
        "streaming_gen_items_per_s_headpath": round(stream_head_rate, 1),
        "baseline_tasks_async_per_s": 8032.4,
        "baseline_actor_sync_per_s": 1985.8,
        "baseline_put_gb_per_s": 18.52,
        "baseline_multi_client_put_per_s": 15931.8,
        "baseline_multi_client_put_gb_per_s": 47.39,
        "baseline_multi_client_tasks_async_per_s": 22745.2,
        "baseline_nn_actor_calls_async_per_s": 26441.7,
    })
    return sync_rate


def bench_envelope(extras):
    """Single-node scalability envelope (reference:
    release/benchmarks/README.md:27-31 + the committed results in
    release/perf_metrics/scalability/single_node.json — 10k args
    17.28s, 3k returns 5.81s, 10k-object get 23.88s, 1M queued 193s,
    100 GiB put+get 30.34s on an m4.16xlarge). The 1M-queued row is a
    REAL 1M run when the budget allows (falls back to a labeled
    extrapolation otherwise), and the big-object row sizes itself to
    the remaining budget from a measured probe (this box's
    thin-provisioned page allocator makes fresh-page touch the wall —
    see docs/TASK_THROUGHPUT_ROOFLINE.md)."""
    if _budget_left() < 180:
        extras["envelope_skipped"] = "bench budget exhausted"
        return
    try:
        import shutil

        import numpy as np

        import ray_tpu
        free_shm = shutil.disk_usage("/dev/shm").free
        store_cap = None
        if free_shm > 64 << 30:
            # The arena is a sparse mmap — a high cap costs nothing
            # until touched, and the big-object row below needs it.
            store_cap = 56 << 30
        ray_tpu.init(num_cpus=min(os.cpu_count() or 4, 16),
                     object_store_memory=store_cap)

        @ray_tpu.remote
        def many_args(*args):
            return len(args)

        @ray_tpu.remote
        def nop():
            return 1

        refs = [ray_tpu.put(i) for i in range(10000)]
        t0 = time.perf_counter()
        assert ray_tpu.get(many_args.remote(*refs)) == 10000
        extras["env_10k_args_s"] = round(time.perf_counter() - t0, 2)
        del refs

        @ray_tpu.remote(num_returns=3000)
        def many_returns():
            return tuple(range(3000))

        t0 = time.perf_counter()
        out = ray_tpu.get(list(many_returns.remote()))
        assert out[-1] == 2999
        extras["env_3k_returns_s"] = round(time.perf_counter() - t0, 2)

        refs = [ray_tpu.put(np.zeros(100)) for _ in range(10000)]
        t0 = time.perf_counter()
        ray_tpu.get(refs)
        extras["env_10k_get_s"] = round(time.perf_counter() - t0, 2)
        del refs

        n_q = 100_000
        t0 = time.perf_counter()
        refs = [nop.remote() for _ in range(n_q)]
        ray_tpu.get(refs)
        dt = time.perf_counter() - t0
        extras["env_100k_queued_s"] = round(dt, 2)
        del refs

        # REAL 1M queued (reference: 193 s measured on an m4.16xlarge)
        # when the remaining budget covers the projected wall +
        # headroom; superlinear effects (queue memory, GC pressure,
        # scheduler scans) are exactly what this row exists to catch.
        projected_1m = dt * 10.0
        if _budget_left() > projected_1m * 1.6 + 120:
            t0 = time.perf_counter()
            refs = [nop.remote() for _ in range(1_000_000)]
            ray_tpu.get(refs)
            extras["env_1m_queued_s"] = round(
                time.perf_counter() - t0, 1)
            del refs
        else:
            extras["env_1m_queued_s"] = round(projected_1m, 1)
            extras["env_1m_queued_estimated"] = True

        # Big object put+get: run the largest of {48, 32, 16, 8, 4}
        # GiB that fits the remaining budget and /dev/shm (>=32 GiB is
        # the envelope target; smaller runs carry the measured-ceiling
        # label). Cost model is MEASURED AT SCALE on this box, not
        # probed small: fresh-page touch collapses superlinearly on the
        # thin-provisioned allocator (2 GiB probes run ~6x faster per
        # GiB than 16 GiB runs), so a small probe wildly under-gates.
        # Measured: source alloc+touch ~9 s/GiB, put+get ~7.5 s/GiB at
        # 16 GiB -> ~17 s/GiB end-to-end wall per candidate.
        per_gib_wall = 17.0

        def _mem_available() -> int:
            # shm free is NOT a proxy for RAM: the numpy source is
            # anonymous process memory and tmpfs pages are RAM-backed
            # too — gate on MemAvailable or the OOM killer ends the
            # bench at the big candidates.
            try:
                for line in open("/proc/meminfo"):
                    if line.startswith("MemAvailable:"):
                        return int(line.split()[1]) * 1024
            except OSError:
                pass
            return 0

        gib = 0
        for cand in (48, 32, 16, 8, 4):
            need_bytes = (cand << 30) * 2 + (8 << 30)  # src + store
            if (shutil.disk_usage("/dev/shm").free > need_bytes
                    and _mem_available() > need_bytes
                    and _budget_left() > cand * per_gib_wall + 90):
                gib = cand
                break
        if gib:
            big = np.zeros((gib << 30,), dtype=np.uint8)
            # Source pages materialize OUTSIDE the timed window (the
            # probe did the same): the row measures the store's
            # put+get, not numpy allocation.
            big[::4096] = 1
            t0 = time.perf_counter()
            got = ray_tpu.get(ray_tpu.put(big))
            assert got.nbytes == big.nbytes
            extras["env_big_put_get_gib"] = gib
            extras["env_big_put_get_s"] = round(
                time.perf_counter() - t0, 2)
            if gib < 32:
                extras["env_big_put_get_ceiling_note"] = (
                    "largest size fitting the bench budget on this "
                    "box's ~17 s/GiB page-allocator wall")
            del big, got
        else:
            extras["env_big_put_get_skipped"] = (
                "budget/shm too small for any candidate size")
        extras.update({
            "baseline_env_10k_args_s": 17.28,
            "baseline_env_3k_returns_s": 5.81,
            "baseline_env_10k_get_s": 23.88,
            "baseline_env_1m_queued_s": 193.0,
        })
    except Exception as e:
        extras["envelope_error"] = f"{type(e).__name__}: {e}"
    finally:
        try:
            import ray_tpu
            ray_tpu.shutdown()
        except Exception:
            pass


def _serve_http_setup(warm_reqs: int = 50):
    """Shared scaffold of the serve HTTP rows (the full-bench section
    AND the `--focus serve_http_req_per_s` metric measure the same
    thing): deploy the nop app, return (mkconn, run_load) where
    run_load(seconds, threads) drives the 16-way closed loop and
    returns (latencies, elapsed). Caller owns serve/runtime teardown."""
    import http.client
    import threading

    from ray_tpu import serve

    serve.start()

    @serve.deployment(max_ongoing_requests=64, num_replicas=2)
    def nop(request):
        return "ok"

    serve.run(nop.bind(), name="bench", route_prefix="/nop")
    host, port = serve.proxy_address().replace("http://", "").split(":")

    def mkconn():
        c = http.client.HTTPConnection(host, int(port))
        c.connect()
        return c

    warm = mkconn()
    for _ in range(warm_reqs):
        warm.request("POST", "/nop", body=b"{}")
        warm.getresponse().read()

    def run_load(seconds: float = 4.0, nthreads: int = 16):
        lat = []
        stop_at = time.time() + seconds

        def worker():
            conn = mkconn()
            while time.time() < stop_at:
                t0 = time.perf_counter()
                conn.request("POST", "/nop", body=b"{}")
                conn.getresponse().read()
                lat.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=worker)
                   for _ in range(nthreads)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lat, time.time() - t0

    return mkconn, run_load


def bench_serve(extras):
    """HTTP data-plane micro-bench (VERDICT r1 #9: nop deployment
    req/s + p50 through the async proxy)."""
    try:
        import ray_tpu
        from ray_tpu import serve

        ray_tpu.init(num_cpus=min(os.cpu_count() or 4, 16))
        mkconn, run_load = _serve_http_setup()

        # Serial p50: request latency without client-side queueing (the
        # 16-way p50 below measures queue depth on small boxes, not the
        # proxy).
        warm = mkconn()
        slat = []
        stop_serial = time.time() + 2.0
        while time.time() < stop_serial:
            t0 = time.perf_counter()
            warm.request("POST", "/nop", body=b"{}")
            warm.getresponse().read()
            slat.append(time.perf_counter() - t0)
        slat.sort()
        extras["serve_http_p50_serial_ms"] = round(
            1000 * slat[len(slat) // 2], 2) if slat else None

        lat, el = run_load()
        lat.sort()
        extras["serve_http_req_per_s"] = round(len(lat) / el, 1)
        extras["serve_http_p50_ms"] = round(
            1000 * lat[len(lat) // 2], 2) if lat else None
        serve.shutdown()
        ray_tpu.shutdown()
    except Exception as e:
        extras["serve_bench_error"] = f"{type(e).__name__}: {e}"
        try:
            import ray_tpu
            ray_tpu.shutdown()
        except Exception:
            pass


def bench_broadcast(extras):
    """Cross-node object broadcast through real daemon nodes (reference:
    1 GiB broadcast scalability test, release/benchmarks/README.md:15)."""
    try:
        import numpy as np

        import ray_tpu
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
        n_nodes = 2
        for i in range(n_nodes):
            cluster.add_node(num_cpus=1, resources={f"n{i}": 1},
                             daemon=True)
        payload = np.zeros((1 << 28,), dtype=np.uint8)  # 256 MB
        ref = ray_tpu.put(payload)

        @ray_tpu.remote
        def consume(a):
            return int(a[0]) + a.nbytes

        # warm: first pull establishes transfer connections
        ray_tpu.get([consume.options(resources={f"n{i}": 1}).remote(ref)
                     for i in range(n_nodes)])
        time.sleep(1.0)  # let the previous section's processes exit
        # Best of 3: a single trial is hostage to teardown noise from
        # the preceding bench section (measured 0.36 vs 4.1 GB/s for
        # the same code on a quiet box).
        best_dt = float("inf")
        for _ in range(3):
            ref2 = ray_tpu.put(payload)
            t0 = time.perf_counter()
            ray_tpu.get([
                consume.options(resources={f"n{i}": 1}).remote(ref2)
                for i in range(n_nodes)])
            best_dt = min(best_dt, time.perf_counter() - t0)
            del ref2
        extras["broadcast_256mb_nodes"] = n_nodes
        extras["broadcast_gb_per_s"] = round(
            n_nodes * payload.nbytes / best_dt / 1e9, 2)
        # Same-host transfers adopt the source arena slot zero-copy
        # (cross-process pins), so virtual-node "broadcasts" move
        # headers, not bytes — flagged here so the GB/s figures are
        # read as what they are. Cross-HOST transfers still copy.
        from ray_tpu._private.config import ray_config as _rc
        extras["broadcast_zero_copy"] = bool(_rc.same_host_adoption)

        # Push-tree broadcast primitive (reference: push_manager.h) —
        # best of 3 (first tree run still faults pages).
        from ray_tpu.experimental import broadcast_object
        best = 0.0
        for _ in range(3):
            ref3 = ray_tpu.put(payload)
            t0 = time.perf_counter()
            n = broadcast_object(ref3)
            dt = time.perf_counter() - t0
            best = max(best, (n - 1) * payload.nbytes / dt / 1e9)
            del ref3
        extras["broadcast_tree_gb_per_s"] = round(best, 2)

        # 8-node broadcast (reference: the 1 GiB-to-N-nodes scalability
        # bench). Uses a true 1 GiB object when /dev/shm can hold
        # 9 copies + slack; falls back to 256 MB otherwise.
        import shutil
        free_shm = shutil.disk_usage("/dev/shm").free
        if _budget_left() > 120 and free_shm > 4 * (1 << 30):
            for i in range(n_nodes, 8):
                cluster.add_node(num_cpus=1, resources={f"n{i}": 1},
                                 daemon=True)
            if free_shm > 12 * (1 << 30) and _budget_left() > 300:
                # ~70 s of copies on a 1-core box; needs budget slack.
                payload8 = np.zeros((1 << 30,), dtype=np.uint8)  # 1 GiB
            else:
                payload8 = payload
            broadcast_object(ray_tpu.put(
                np.zeros(1 << 20, dtype=np.uint8)))  # warm conns
            best = 0.0
            trials = 2 if _budget_left() > 180 else 1
            for _ in range(trials):
                ref8 = ray_tpu.put(payload8)
                t0 = time.perf_counter()
                n = broadcast_object(ref8)
                dt = time.perf_counter() - t0
                best = max(best,
                           (n - 1) * payload8.nbytes / dt / 1e9)
                del ref8
            extras["broadcast8_nodes"] = n
            extras["broadcast8_mb"] = payload8.nbytes >> 20
            extras["broadcast8_gb_per_s"] = round(best, 2)
        cluster.shutdown()
    except Exception as e:
        extras["broadcast_bench_error"] = f"{type(e).__name__}: {e}"
        try:
            cluster.shutdown()  # daemons must not leak into TPU benches
        except Exception:
            try:
                import ray_tpu
                ray_tpu.shutdown()
            except Exception:
                pass


def bench_pull(extras):
    """Worker-to-worker object pulls through real daemon nodes: the
    direct transfer plane (PULL_DIRECT chunk streams over brokered
    channels) vs the daemon-relayed path, measured consumer-side on
    the same cluster in the same run (reference: object manager
    Push/Pull chunked transfers, object_manager.cc)."""
    try:
        import ray_tpu
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
        cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
        cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

        @ray_tpu.remote(resources={"A": 1})
        class Producer:
            def make(self, nbytes, i):
                import numpy as np
                return np.full(nbytes, i % 251, dtype=np.uint8)

            def ping(self):
                return True

        @ray_tpu.remote(resources={"B": 1})
        class Consumer:
            def set_direct(self, on):
                from ray_tpu._private.config import ray_config
                ray_config.set("direct_object_transfer_enabled",
                               bool(on))
                return True

            def pull(self, producer, n_objs, nbytes):
                # Production excluded from the clock: the actor runs
                # its makes serially, so the ping barrier means every
                # object is sealed before timing starts.
                refs = [producer.make.remote(nbytes, i)
                        for i in range(n_objs)]
                ray_tpu.get(producer.ping.remote())
                t0 = time.perf_counter()
                total = 0
                for r in refs:
                    total += ray_tpu.get(r).nbytes
                return total / (time.perf_counter() - t0) / 1e9

        prod = Producer.remote()
        cons = Consumer.remote()
        # Warm: brokers the direct channel + faults in both stores.
        ray_tpu.get(cons.pull.remote(prod, 1, 1 << 20))

        size, n_objs = 64 << 20, 4
        direct = max(ray_tpu.get(cons.pull.remote(prod, n_objs, size))
                     for _ in range(3))
        ray_tpu.get(cons.set_direct.remote(False))
        daemon_path = max(
            ray_tpu.get(cons.pull.remote(prod, n_objs, size))
            for _ in range(3))
        ray_tpu.get(cons.set_direct.remote(True))
        extras["pull_gb_per_s"] = round(direct, 2)
        extras["pull_gb_per_s_daemon_path"] = round(daemon_path, 2)
        cluster.shutdown()
    except Exception as e:
        extras["pull_bench_error"] = f"{type(e).__name__}: {e}"
        try:
            cluster.shutdown()
        except Exception:
            try:
                import ray_tpu
                ray_tpu.shutdown()
            except Exception:
                pass


def bench_shuffle(extras):
    """Streaming all-to-all exchange (data/shuffle.py: reducer actors
    pulling shard sets over the direct transfer plane as maps land) vs
    the bulk two-phase path (_bulk_shuffle: full map barrier, then
    reduce tasks, every output block landed serially on the driver) —
    same seeded random_shuffle, same 2-node daemon cluster, measured
    end-to-end as driver-consumed output bytes per second."""
    try:
        import ray_tpu
        import ray_tpu.data as rdata
        from ray_tpu.cluster_utils import Cluster
        from ray_tpu.data.context import DataContext

        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
        cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
        cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

        ctx = DataContext.get_current()
        ctx.shuffle_partitions = 8
        rows = 24_000_000  # 183 MB of int64 through the exchange

        def consume(refs):
            total = 0
            for ref in refs:
                total += sum(v.nbytes
                             for v in ray_tpu.get(ref).values())
            return total

        def run_streaming():
            ctx.use_streaming_shuffle = True
            ds = rdata.range(rows, override_num_blocks=16) \
                .random_shuffle(seed=1)
            t0 = time.perf_counter()
            total = consume(r for r, _ in ds._iter_bundles())
            return total / (time.perf_counter() - t0) / 1e9

        def run_barrier():
            # The exchange's predecessor on the same consumption path:
            # the in-executor task-based shuffle operator.
            ctx.use_streaming_shuffle = False
            ds = rdata.range(rows, override_num_blocks=16) \
                .random_shuffle(seed=1)
            t0 = time.perf_counter()
            total = consume(r for r, _ in ds._iter_bundles())
            return total / (time.perf_counter() - t0) / 1e9

        def run_bulk():
            # plan.execute() always runs the bulk stage_fn; the flag
            # only routes _iter_bundles.
            ds = rdata.range(rows, override_num_blocks=16) \
                .random_shuffle(seed=1)
            t0 = time.perf_counter()
            total = consume(b.ref for b in ds._plan.execute())
            return total / (time.perf_counter() - t0) / 1e9

        run_streaming()  # warm: reducer actor spawn + channel brokering
        streaming = max(run_streaming() for _ in range(3))
        barrier = max(run_barrier() for _ in range(3))
        bulk = max(run_bulk() for _ in range(3))
        extras["shuffle_gb_per_s"] = round(streaming, 3)
        extras["shuffle_gb_per_s_barrier_path"] = round(barrier, 3)
        extras["shuffle_gb_per_s_bulk_path"] = round(bulk, 3)
        extras["shuffle_streaming_vs_bulk"] = round(
            streaming / bulk, 2) if bulk else None
        extras["shuffle_rows"] = rows
        cluster.shutdown()
    except Exception as e:
        extras["shuffle_bench_error"] = f"{type(e).__name__}: {e}"
        try:
            cluster.shutdown()
        except Exception:
            try:
                import ray_tpu
                ray_tpu.shutdown()
            except Exception:
                pass


def bench_resnet(extras):
    """ResNet-50 batch inference through Data map_batches actor pools
    (BASELINE config #3). Runs BEFORE the driver touches the TPU so the
    pool actor can own the chip. Budget-gated: pays a full in-actor XLA
    compile (~2 min) plus tunnel-bound batch uploads."""
    if _budget_left() < 540:
        # Needs ~240s itself AND must leave ~300s for the GPT section's
        # unconditional compile that follows.
        extras["resnet_pipeline_skipped"] = "bench budget exhausted"
        return
    try:
        import numpy as np

        import ray_tpu
        from ray_tpu import data as rdata
        from ray_tpu._private.resources import TPUAcceleratorManager

        n_chips = TPUAcceleratorManager.get_current_node_num_accelerators()
        if n_chips < 1:
            return
        ray_tpu.init()

        class Predictor:
            """Reports per-call completion times through the GCS KV so
            the driver can compute the STEADY-STATE rate (first batches
            pay the ~30 s XLA compile; iter_batches timestamps are
            useless because blocks surface after execution completes).
            The first call also measures the upload + compute rates so
            the driver can print the environment's own feed CEILING
            next to the achieved rate (VERDICT r4 next #3)."""

            def __init__(self):
                import threading
                import time as _t

                import jax

                from ray_tpu.models import ResNetConfig, make_predictor
                self.predict = make_predictor(ResNetConfig.resnet50())
                self.calls = 0
                self._t = _t
                self._lock = threading.Lock()  # max_concurrency=2
                # Ceiling probe AT CONSTRUCTION, before any pipelined
                # batch can contend for the chip/tunnel (a probe taken
                # mid-stream under max_concurrency=2 would time a
                # contended upload and understate the ceiling).
                #
                # r6 coherence fix (VERDICT weak #5: the pipeline "beat"
                # its own ceiling 2.2x): the old probe timed
                # jax.device_put WITH np.random generation inside the
                # timed region (~38M doubles — dominating the upload),
                # and on a different code path than the pipeline uses.
                # Now: buffers are generated OUTSIDE every timer, the
                # compute term is predict() on a device-resident batch,
                # and the upload term is measured ON THE PIPELINE'S OWN
                # PATH — uncontended end-to-end predict(host_numpy)
                # minus the compute term. Fresh buffers per measurement:
                # re-uploading warm pages measures the cache, not the
                # tunnel.
                probe = np.random.rand(64, 224, 224, 3).astype(
                    np.float32)
                np.asarray(self.predict(probe))  # XLA compile
                d = jax.device_put(probe)
                d.block_until_ready()
                t0 = _t.perf_counter()
                np.asarray(self.predict(d))
                comp_s = _t.perf_counter() - t0
                fresh = np.random.rand(64, 224, 224, 3).astype(
                    np.float32)
                t0 = _t.perf_counter()
                np.asarray(self.predict(fresh))
                e2e_s = _t.perf_counter() - t0
                up_s = max(e2e_s - comp_s, 0.0)
                try:
                    from ray_tpu._private import state as _state
                    _state.current().gcs_request(
                        "kv_put", key="resnet_bench/rates",
                        value=f"{up_s}:{comp_s}:{e2e_s}".encode(),
                        namespace="bench")
                except Exception:
                    pass

            def __call__(self, batch):
                batch["label"] = np.asarray(self.predict(batch["image"]))
                with self._lock:
                    self.calls += 1
                    calls = self.calls
                try:
                    from ray_tpu._private import state as _state
                    _state.current().gcs_request(
                        "kv_put", key=f"resnet_bench/{calls}",
                        value=f"{len(batch['label'])}:"
                              f"{self._t.perf_counter()}".encode(),
                        namespace="bench")
                except Exception:
                    pass
                return batch

        n_images, bs = 512, 64
        rng = np.random.default_rng(0)
        ds = rdata.from_items([
            {"image": rng.normal(size=(224, 224, 3)).astype(np.float32)}
            for _ in range(n_images)])
        # max_concurrency=2: batch N+1's upload overlaps batch N's
        # compute + label fetch (jax async dispatch), so the tunnel is
        # the only serial term in steady state.
        out = ds.map_batches(Predictor, batch_size=bs, concurrency=1,
                             num_tpus=1, max_concurrency=2)
        out.materialize()
        from ray_tpu._private import state as _state
        rt = _state.current()
        marks = []
        for i in range(1, n_images // bs + 2):
            raw = rt.gcs_request("kv_get", key=f"resnet_bench/{i}",
                                 namespace="bench")
            if raw is None:
                break
            n_str, t_str = raw.decode().split(":")
            marks.append((int(n_str), float(t_str)))
        if len(marks) > 3:
            # Steady state: from the end of call 2 to the last call.
            # NOTE: through the axon tunnel this is host->device
            # bandwidth-bound (each 64-image batch uploads 38 MB); the
            # device-resident compute rate is reported separately by
            # bench_tpu.
            n_steady = sum(n for n, _ in marks[2:])
            dt = marks[-1][1] - marks[1][1]
            extras["resnet50_pipeline_images_per_s"] = round(
                n_steady / dt, 1)
            extras["resnet50_batches"] = len(marks)
            raw = rt.gcs_request("kv_get", key="resnet_bench/rates",
                                 namespace="bench")
            if raw is not None:
                parts = [float(v) for v in raw.decode().split(":")]
                up_s, comp_s = parts[0], parts[1]
                # With upload/compute overlapped, the feed ceiling is
                # the SLOWER of the two terms, not their sum. The
                # upload term is e2e-minus-compute on the pipeline's
                # own predict(host_batch) path (see the probe), so the
                # achieved rate is coherent with — and bounded by —
                # this ceiling.
                ceiling = bs / max(up_s, comp_s, 1e-9)
                extras["resnet50_upload_s_per_batch"] = round(up_s, 3)
                extras["resnet50_compute_s_per_batch"] = round(comp_s, 3)
                if len(parts) > 2:
                    extras["resnet50_uncontended_e2e_s_per_batch"] = \
                        round(parts[2], 3)
                extras["resnet50_pipeline_ceiling_img_per_s"] = round(
                    ceiling, 1)
                extras["resnet50_pipeline_vs_ceiling"] = round(
                    extras["resnet50_pipeline_images_per_s"] / ceiling,
                    3)
                extras["resnet50_ceiling_method"] = (
                    "upload = uncontended e2e predict(host batch) minus "
                    "device-resident compute, same code path as the "
                    "pipeline (r6 fix; pre-r6 numbers timed device_put "
                    "with buffer generation inside the timer and are "
                    "not comparable)")
        ray_tpu.shutdown()
    except Exception as e:
        extras["resnet_bench_error"] = f"{type(e).__name__}: {e}"
        try:
            import ray_tpu
            ray_tpu.shutdown()
        except Exception:
            pass


_CHIP_PEAK_BF16 = {
    # TFLOP/s per chip, bf16 (public spec sheets).
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _chip_peak(device) -> float:
    kind = getattr(device, "device_kind", "")
    for name, peak in sorted(_CHIP_PEAK_BF16.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(name):
            return peak
    return 197e12  # conservative default: v5e


def bench_tpu(extras):
    """GPT-2-small (124M) train step with MFU (VERDICT r1 #5): model
    FLOPs via the standard 6*N*tokens estimate AND XLA cost_analysis,
    against the chip's published bf16 peak."""
    try:
        import dataclasses

        import jax
        if jax.devices()[0].platform != "tpu":
            return
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models import GPTConfig, make_train_step

        # remat off: GPT-2-small at B=16/S=1024 fits v5e HBM without it
        # and runs ~25% faster (chunked loss keeps the logits small).
        cfg = dataclasses.replace(GPTConfig.gpt2_small(), remat=False)
        init_state, train_step = make_train_step(cfg)
        state = init_state(jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(x.shape))
                       for x in jax.tree.leaves(state["params"]))
        B, S = 16, 1024
        tokens = np.random.randint(0, cfg.vocab_size, (B, S),
                                   dtype=np.int32)
        batch = (jnp.asarray(tokens), jnp.asarray(np.roll(tokens, -1, 1)))
        state, m = train_step(state, batch)  # compile
        float(m["loss"])
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = train_step(state, batch)
        # Sync via VALUE FETCH: on the axon tunnel backend
        # jax.block_until_ready can return before device execution
        # completes (measured: it reported a physically impossible
        # 0.9 ms/step — 77x chip peak — while the loss fetch took 30 s),
        # so only a materialized output is an honest barrier.
        float(m["loss"])
        dt = (time.perf_counter() - t0) / iters
        # XLA-counted FLOPs AFTER timing (an extra lower().compile() on
        # this backend also perturbs subsequent dispatch). It is a
        # second full compile (~minutes on the remote-compile tunnel),
        # so it only runs inside budget.
        xla_flops = 0.0
        if _budget_left() > 240:
            try:
                cost = jax.jit(train_step).lower(
                    state, batch).compile().cost_analysis()
                if isinstance(cost, list):
                    cost = cost[0]
                xla_flops = float(cost.get("flops", 0.0))
            except Exception:
                pass
        peak = _chip_peak(jax.devices()[0])
        tokens_per_s = B * S / dt
        # Standard MFU: 6*N FLOPs per token for fwd+bwd.
        model_flops = 6.0 * n_params * B * S
        extras["tpu_train_tokens_per_s"] = round(tokens_per_s, 1)
        extras["tpu_train_step_ms"] = round(dt * 1e3, 2)
        extras["tpu_model"] = f"gpt2-small-{n_params/1e6:.0f}M-bf16"
        extras["tpu_chip"] = getattr(jax.devices()[0], "device_kind", "?")
        extras["tpu_peak_bf16_tflops"] = round(peak / 1e12, 1)
        extras["mfu"] = round(model_flops / dt / peak, 4)
        if xla_flops:
            extras["mfu_xla_counted"] = round(xla_flops / dt / peak, 4)
            extras["xla_flops_per_step"] = xla_flops

        # -- llama-class flagship MFU (VERDICT r3 #4): head_dim 128,
        # GQA, S=2048, bf16 — the TPU-shaped headline. MFU accounting:
        # `mfu` (6*N*D analytic) is the HEADLINE everywhere in this
        # bench — it is the industry-standard comparable number;
        # `mfu_xla_counted` divides XLA's own per-op FLOP count by the
        # same wall time and runs lower because cost_analysis counts
        # only compiled-graph FLOPs (no recompute credit, different
        # attention accounting) — reported as a cross-check, not the
        # claim. --
        if _budget_left() > 240:
            from ray_tpu.models import LlamaConfig, make_llama_train_step
            lcfg = LlamaConfig.tpu_bench()
            l_init, l_step = make_llama_train_step(lcfg)
            l_state = l_init(jax.random.PRNGKey(1))
            l_params = sum(int(np.prod(x.shape))
                           for x in jax.tree.leaves(l_state["params"]))
            # B=8 amortizes the non-matmul overhead ~4.5% better than
            # B=4 (0.561 vs 0.537 MFU measured on v5e; fits HBM with
            # remat off at this model size).
            LB, LS = 8, 2048
            ltok = np.random.randint(0, lcfg.vocab_size, (LB, LS),
                                     dtype=np.int32)
            lbatch = (jnp.asarray(ltok),
                      jnp.asarray(np.roll(ltok, -1, 1)))
            l_state, lm = l_step(l_state, lbatch)  # compile
            float(lm["loss"])
            liters = 10
            t0 = time.perf_counter()
            for _ in range(liters):
                l_state, lm = l_step(l_state, lbatch)
            float(lm["loss"])  # value fetch = honest sync (see above)
            ldt = (time.perf_counter() - t0) / liters
            extras["llama_model"] = (
                f"llama-{l_params/1e6:.0f}M-hd128-gqa4-bf16")
            extras["llama_tokens_per_s"] = round(LB * LS / ldt, 1)
            extras["llama_step_ms"] = round(ldt * 1e3, 2)
            extras["llama_mfu"] = round(
                6.0 * l_params * LB * LS / ldt / peak, 4)
            extras["mfu_headline"] = "llama_mfu (6ND analytic)"
            # XLA-counted cross-check for the FLAGSHIP headline too
            # (VERDICT r4 next #7): same wall time, XLA's own per-op
            # FLOP count — a second full compile, so budget-gated.
            if _budget_left() > 300:
                try:
                    lcost = jax.jit(l_step).lower(
                        l_state, lbatch).compile().cost_analysis()
                    if isinstance(lcost, list):
                        lcost = lcost[0]
                    l_xla = float(lcost.get("flops", 0.0))
                    if l_xla:
                        extras["llama_mfu_xla_counted"] = round(
                            l_xla / ldt / peak, 4)
                        extras["llama_xla_flops_per_step"] = l_xla
                except Exception:
                    pass
        else:
            extras["llama_mfu_skipped"] = "bench budget exhausted"

        # -- host<->device tunnel bandwidth (explains pipeline numbers
        # on this environment; a real TPU VM moves GB/s over PCIe) ----
        buf = np.random.rand(64, 224, 224, 3).astype(np.float32)
        t0 = time.perf_counter()
        dbuf = jax.device_put(buf)
        dbuf.block_until_ready()
        extras["host_to_device_mb_s"] = round(
            buf.nbytes / (time.perf_counter() - t0) / 1e6, 1)

        # -- ResNet-50 device-resident batch inference (BASELINE config
        # #3's model; input upload excluded — see host_to_device_mb_s).
        # Pays its own driver-side XLA compile: budget-gated. --
        if _budget_left() < 150:
            extras["resnet_device_skipped"] = "bench budget exhausted"
            return
        from ray_tpu.models import ResNetConfig, make_predictor
        pred = make_predictor(ResNetConfig.resnet50())
        logits = pred(dbuf)
        np.asarray(logits)
        t0 = time.perf_counter()
        for _ in range(10):
            logits = pred(dbuf)
        np.asarray(logits)  # value fetch = honest sync (see above)
        rdt = (time.perf_counter() - t0) / 10
        extras["resnet50_images_per_s"] = round(64 / rdt, 1)
    except Exception as e:  # TPU benches are best-effort
        extras["tpu_error"] = f"{type(e).__name__}: {e}"


# ---------------------------------------------------------------------------
# focus metrics + same-session A/B (variance hardening)
#
# `--focus <metric>` measures ONE metric (N reps, spread reported) in a
# fresh runtime — cheap enough to run repeatedly. `--ab <metric>` proves
# a working-tree change on THIS box in one bench session: it runs the
# focus metric on the current tree, `git stash`es the tree back to HEAD,
# runs the SAME script again (copied out first, so the stashed tree's
# older bench.py is never needed), pops the stash, and prints both
# results plus the ratio. Back-to-back on identical machine state, so
# the PR 2 caveat (~1.7x cross-run swings on this box) cancels instead
# of drowning the signal.
# ---------------------------------------------------------------------------
def _focus_tasks_async(ray_tpu):
    @ray_tpu.remote
    def nop():
        return None
    ray_tpu.get([nop.remote() for _ in range(200)])

    def measure():
        n = 5000
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)])
        return n / (time.perf_counter() - t0)
    return measure


def _focus_put_get(ray_tpu):
    import numpy as np
    small = np.zeros(1000, dtype=np.float64)
    ray_tpu.get(ray_tpu.put(small))

    def measure():
        n = 1000
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(ray_tpu.put(small))
        return n / (time.perf_counter() - t0)
    return measure


def _focus_put_gb(ray_tpu):
    import numpy as np
    big = np.zeros((1 << 28,), dtype=np.uint8)  # 256 MB
    ref = ray_tpu.put(big)  # warm: fault in source pages, prime store
    del ref

    def measure():
        iters = 4
        t0 = time.perf_counter()
        for _ in range(iters):
            ref = ray_tpu.put(big)
            del ref
        return iters * big.nbytes / (time.perf_counter() - t0) / 1e9
    return measure


def _focus_put_latency(ray_tpu):
    """Per-put latency of SMALL store puts (4 KiB), in MICROSECONDS —
    lower is better (run_ab's ratio reads inverted for this row: a
    worktree/head ratio under 1.0 is a win). The inline threshold is
    dropped so the puts actually traverse the store write path — this
    row exists to watch the small-put fixed costs the zero-copy path
    targets: segment reservation (pool stripe claim vs fresh
    create+ftruncate) and gate bypass (below host_copy_gate_min_bytes
    no HostCopyGate ticket is taken; tests/test_put_path.py proves the
    zero-ticket contract with a counter)."""
    from ray_tpu._private.config import ray_config
    ray_config.set("inline_object_max_bytes", 0)
    payload = b"\xa5" * 4096
    for _ in range(50):  # warm: pool stripe, serializer, id paths
        ref = ray_tpu.put(payload)
        del ref

    def measure():
        iters = 500
        t0 = time.perf_counter()
        for _ in range(iters):
            ref = ray_tpu.put(payload)
            del ref
        return (time.perf_counter() - t0) / iters * 1e6
    return measure


def _focus_mc_put_gb(ray_tpu):
    """Concurrent store clients: 4 driver-side client threads, each
    putting (and dropping) a 120 MB buffer in a loop against the
    node-shared store — the contention row for the put write path
    (segment recycling, lock hold times). Source pages are faulted in
    before timing so the rounds measure the store, not the source."""
    import numpy as np
    import threading

    data = np.zeros(120 << 20, dtype=np.uint8)
    data[::4096] = 1

    def client(iters):
        for _ in range(iters):
            ref = ray_tpu.put(data)
            del ref

    def round_(iters):
        threads = [threading.Thread(target=client, args=(iters,))
                   for _ in range(4)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return 4 * iters * data.nbytes / (time.perf_counter() - t0) / 1e9

    round_(2)  # warm: pages faulted, store primed

    def measure():
        return round_(4)
    return measure


def _focus_pull_gb(ray_tpu):
    """Consumer-observed cross-node pull bandwidth (the bench_pull
    direct-plane row as a focus metric; on a tree without the transfer
    plane the same scaffold measures the daemon-relayed path, so
    `--ab pull_gb_per_s` is the plane's speedup)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()  # run_focus already init'd the head
    cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

    @ray_tpu.remote(resources={"A": 1})
    class Producer:
        def make(self, nbytes, i):
            import numpy as np
            return np.full(nbytes, i % 251, dtype=np.uint8)

        def ping(self):
            return True

    @ray_tpu.remote(resources={"B": 1})
    class Consumer:
        def pull(self, producer, n_objs, nbytes):
            refs = [producer.make.remote(nbytes, i)
                    for i in range(n_objs)]
            ray_tpu.get(producer.ping.remote())
            t0 = time.perf_counter()
            total = 0
            for r in refs:
                total += ray_tpu.get(r).nbytes
            return total / (time.perf_counter() - t0) / 1e9

    prod = Producer.remote()
    cons = Consumer.remote()
    ray_tpu.get(cons.pull.remote(prod, 1, 1 << 20))  # warm channel

    def measure():
        return ray_tpu.get(cons.pull.remote(prod, 4, 64 << 20))
    return measure


def _focus_shuffle_gb(ray_tpu):
    """End-to-end seeded random_shuffle throughput through the
    STREAMING path (`_iter_bundles`) on a 2-node daemon cluster,
    driver-consumed output bytes/s. Both sides of `--ab` consume the
    same way: a tree with the exchange (data/shuffle.py present) runs
    reducer actors pulling shard sets over the direct plane; a tree
    without it runs its in-executor task-based shuffle operator — so
    the AB ratio is the exchange vs the task-based path it replaced,
    same workload, same consumption API."""
    import ray_tpu.data as rdata
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.context import DataContext

    cluster = Cluster()  # run_focus already init'd the head
    cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

    ctx = DataContext.get_current()
    try:
        ctx.shuffle_partitions = 8
        ctx.use_streaming_shuffle = True
    except AttributeError:
        pass  # pre-exchange tree: knobs absent, barrier op runs
    rows = 24_000_000  # 183 MB: big enough that per-exchange fixed
    # costs (reducer-pool RPCs, channel setup) stop dominating

    def run():
        ds = rdata.range(rows, override_num_blocks=16) \
            .random_shuffle(seed=1)
        t0 = time.perf_counter()
        total = 0
        for ref, _rows in ds._iter_bundles():
            total += sum(v.nbytes for v in ray_tpu.get(ref).values())
        return total / (time.perf_counter() - t0) / 1e9

    run()  # warm: reducer spawn + channel brokering
    return run


def _focus_mc_tasks(ray_tpu):
    @ray_tpu.remote
    def nop():
        return None

    @ray_tpu.remote
    class Submitter:
        def batch(self, n):
            ray_tpu.get([nop.remote() for _ in range(n)])
            return n

    subs = [Submitter.remote() for _ in range(4)]
    ray_tpu.get([s.batch.remote(10) for s in subs])

    def measure():
        per = 500
        t0 = time.perf_counter()
        ray_tpu.get([s.batch.remote(per) for s in subs])
        return len(subs) * per / (time.perf_counter() - t0)
    return measure


def _focus_nn_actor(ray_tpu):
    @ray_tpu.remote
    class NopActor:
        def nop(self):
            return None

    @ray_tpu.remote
    class Caller:
        def __init__(self, callee):
            self.callee = callee

        def drive(self, n):
            ray_tpu.get([self.callee.nop.remote() for _ in range(n)])
            return n

    callees = [NopActor.remote() for _ in range(4)]
    callers = [Caller.remote(c) for c in callees]
    ray_tpu.get([c.drive.remote(10) for c in callers])

    def measure():
        per = 500
        t0 = time.perf_counter()
        ray_tpu.get([c.drive.remote(per) for c in callers])
        return len(callers) * per / (time.perf_counter() - t0)
    return measure


def _focus_streaming_gen(ray_tpu):
    """Caller-observed streaming-generator throughput: a worker caller
    consumes channel streams from a callee actor (since the direct
    plane carries streams this rides GEN_ITEM frames caller<-callee;
    with direct_calls_enabled=0 workers cannot consume streams, so the
    head-path comparison point is the driver consuming the same
    generator)."""
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield i

    @ray_tpu.remote
    class Consumer:
        def __init__(self, g):
            self.g = g

        def consume(self, n):
            got = 0
            for _ref in self.g.stream.options(
                    num_returns="streaming").remote(n):
                got += 1
            return got

    g = Gen.remote()
    c = Consumer.remote(g)
    ray_tpu.get(c.consume.remote(50))  # warm (channel established)

    def measure():
        per = 1000
        t0 = time.perf_counter()
        assert ray_tpu.get(c.consume.remote(per)) == per
        return per / (time.perf_counter() - t0)
    return measure


def _focus_serve_http(ray_tpu):
    """Proxy req/s (the bench_serve 16-thread row as a focus metric, so
    serve changes prove themselves with `--ab serve_http_req_per_s`
    instead of a full bench run). Same scaffold as bench_serve."""
    _mkconn, run_load = _serve_http_setup(warm_reqs=100)

    def measure():
        lat, el = run_load()
        return len(lat) / el
    return measure


def _focus_serve_http_multi(ray_tpu):
    """Aggregate req/s through N proxies x M replicas (the scale shape
    of the direct serve data plane: every proxy holds its OWN brokered
    channels to every replica, so extra proxies add ingress capacity
    without any per-request head involvement). 3 proxies x 4 replicas,
    6 closed-loop client threads per proxy."""
    import http.client
    import threading

    from ray_tpu import serve

    controller = serve.start()

    @serve.deployment(max_ongoing_requests=64, num_replicas=4)
    def nop(request):
        return "ok"

    serve.run(nop.bind(), name="bench_multi", route_prefix="/nop")
    from ray_tpu.serve._private.proxy import HTTPProxy

    # serve.start()'s driver proxy plus two more in-driver proxies;
    # each runs its own router, admission counters, and direct
    # channels (leaked at exit like _focus_serve_http's scaffold —
    # run_focus tears the whole process down right after).
    proxies = [serve._proxy] + [HTTPProxy(controller, "127.0.0.1", 0)
                                for _ in range(2)]
    addrs = [(p.host, p.port) for p in proxies]

    for host, port in addrs:  # warm: channels + verdicts per proxy
        c = http.client.HTTPConnection(host, int(port))
        c.connect()
        for _ in range(50):
            c.request("POST", "/nop", body=b"{}")
            c.getresponse().read()
        c.close()

    def measure():
        lat = []
        stop_at = time.time() + 4.0

        def worker(host, port):
            conn = http.client.HTTPConnection(host, int(port))
            conn.connect()
            while time.time() < stop_at:
                t0 = time.perf_counter()
                conn.request("POST", "/nop", body=b"{}")
                conn.getresponse().read()
                lat.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=worker,
                                    args=addrs[i % len(addrs)])
                   for i in range(18)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return len(lat) / (time.time() - t0)
    return measure


def _focus_head_control(ray_tpu):
    """Head control-plane throughput: 200 stub daemons (real auth +
    REGISTER_NODE over TCP, zero resources, one client-side selector
    thread) pump NODE_PING windows; the value is NODE_SYNC acks/s —
    each ack is one full ping -> head route -> sync round trip, so it
    prices the head's per-message cost including the O(N) view fanout.
    Self-contained on purpose: --ab replays this closure inside the
    stashed HEAD tree, so it only touches long-stable internals
    (state.get_node, head_server.address, cluster_token, protocol
    framing)."""
    import os as _os
    import selectors
    import socket as _socket
    import threading
    from multiprocessing.connection import Client

    from ray_tpu._private import protocol as _P
    from ray_tpu._private import state as _state

    node = _state.get_node()
    address = tuple(node.head_server.address)
    token = node.cluster_token

    n_stubs = 200
    conns = []
    sel = selectors.DefaultSelector()
    counts = {"acked": 0, "synced": 0}
    lock = threading.Lock()
    stop = threading.Event()

    for i in range(n_stubs):
        conn = Client(address, family="AF_INET", authkey=token)
        payload = {"node_id_hex": f"{0xbe9c0000 + i:08x}" + "00" * 12,
                   "resources": {}, "transfer_port": 0,
                   "hostname": f"bench-stub-{i}", "pid": 0, "labels": {}}
        conn.send_bytes(_P.dump_message(_P.REGISTER_NODE, payload))
        sock = _socket.socket(fileno=_os.dup(conn.fileno()))
        sel.register(sock, selectors.EVENT_READ,
                     (sock, _P.FrameParser()))
        conns.append(conn)

    scratch = bytearray(1 << 20)
    view = memoryview(scratch)

    def pump_recv():
        while not stop.is_set():
            for key, _ in sel.select(timeout=0.2):
                sock, parser = key.data
                while True:
                    try:
                        r = sock.recv_into(scratch, len(scratch),
                                           _socket.MSG_DONTWAIT)
                    except (BlockingIOError, InterruptedError):
                        break
                    except OSError:
                        r = 0
                    if r == 0:
                        try:
                            sel.unregister(sock)
                        except (KeyError, ValueError):
                            pass
                        break
                    parser.feed(view[:r])
                n_ack = n_sync = 0
                for msg_type, _payload in parser.messages():
                    if msg_type == _P.NODE_SYNC:
                        n_sync += 1
                    elif msg_type == _P.NODE_ACK:
                        n_ack += 1
                if n_ack or n_sync:
                    with lock:
                        counts["acked"] += n_ack
                        counts["synced"] += n_sync

    threading.Thread(target=pump_recv, daemon=True,
                     name="bench-stub-swarm").start()
    deadline = time.time() + 60
    while time.time() < deadline:
        with lock:
            if counts["acked"] >= n_stubs:
                break
        time.sleep(0.02)
    with lock:
        if counts["acked"] < n_stubs:
            raise RuntimeError(
                f"only {counts['acked']}/{n_stubs} stub daemons acked")

    def measure():
        # The stub fleet (and its registered head-side state) is leaked
        # at exit like the serve scaffolds — run_focus tears the whole
        # process down right after the reps.
        rounds = 8
        with lock:
            start = counts["synced"]
        payload = {"ts": time.time(), "store_used": 0,
                   "num_workers": 0, "free_chips": 0, "pool_workers": 0}
        frame = _P.dump_message(_P.NODE_PING, payload)
        sent = 0
        t0 = time.perf_counter()
        for _ in range(rounds):
            for conn in conns:
                try:
                    conn.send_bytes(frame)
                except OSError:
                    pass
                else:
                    sent += 1
        want = start + sent
        wait_until = time.time() + 120
        while time.time() < wait_until:
            with lock:
                if counts["synced"] >= want:
                    break
            time.sleep(0.005)
        dt = time.perf_counter() - t0
        with lock:
            done = counts["synced"] - start
        return done / dt
    return measure


FOCUS_METRICS = {
    "tasks_async_per_s": _focus_tasks_async,
    "put_get_per_s": _focus_put_get,
    "put_gb_per_s": _focus_put_gb,
    "put_latency_us": _focus_put_latency,
    "multi_client_put_gb_per_s": _focus_mc_put_gb,
    "pull_gb_per_s": _focus_pull_gb,
    "shuffle_gb_per_s": _focus_shuffle_gb,
    "multi_client_tasks_async_per_s": _focus_mc_tasks,
    "nn_actor_calls_async_per_s": _focus_nn_actor,
    "streaming_gen_items_per_s": _focus_streaming_gen,
    "serve_http_req_per_s": _focus_serve_http,
    "serve_http_multi": _focus_serve_http_multi,
    "head_control_msgs_per_s": _focus_head_control,
}


def run_focus(name: str, reps: int = 3) -> None:
    if name not in FOCUS_METRICS:
        print(json.dumps({"error": f"unknown focus metric {name}; "
                          f"known: {sorted(FOCUS_METRICS)}"}))
        sys.exit(2)
    import ray_tpu
    ray_tpu.init(num_cpus=min(os.cpu_count() or 4, 16))
    try:
        measure = FOCUS_METRICS[name](ray_tpu)
        vals = sorted(measure() for _ in range(max(1, reps)))
    finally:
        ray_tpu.shutdown()
    # 3 decimals: GB/s-denominated metrics sit well below 1.0 on small
    # hosts and a 1-decimal round collapses them to 0.0 (and --ab
    # ratios computed from them to garbage).
    print(json.dumps({
        "metric": name, "value": round(vals[-1], 3),
        "spread": [round(vals[0], 3), round(statistics.median(vals), 3),
                   round(vals[-1], 3)]}))


def run_ab(name: str, reps: int = 3) -> None:
    import shutil
    import subprocess
    import tempfile
    repo = os.path.dirname(os.path.abspath(__file__))
    # The SAME (current) bench script measures both sides — the stashed
    # tree's bench.py may predate --focus.
    script = os.path.join(tempfile.mkdtemp(prefix="bench_ab_"),
                          "bench_ab.py")
    shutil.copy2(os.path.abspath(__file__), script)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")

    def one(side: str):
        p = subprocess.run(
            [sys.executable, script, "--focus", name, "--reps",
             str(reps)], capture_output=True, text=True, cwd=repo,
            env=env, timeout=600)
        for line in reversed(p.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        return {"error": f"{side} run produced no result line",
                "stderr": p.stderr[-2000:]}

    def git(*args):
        # LC_ALL=C: never parse localized porcelain output.
        genv = dict(os.environ, LC_ALL="C", LANG="C")
        return subprocess.run(["git", *args], cwd=repo,
                              capture_output=True, text=True, env=genv)

    def stash_ref():
        return git("rev-parse", "-q", "--verify",
                   "refs/stash").stdout.strip()

    worktree = one("worktree")
    # "Did the push actually stash?" is answered by refs/stash moving,
    # not by string-matching git's message — so a clean tree can never
    # lead to popping someone's unrelated pre-existing stash entry.
    before_ref = stash_ref()
    stash = git("stash", "push", "-m", "bench-ab")
    stashed = stash.returncode == 0 and stash_ref() != before_ref
    try:
        head = one("HEAD") if stashed else dict(
            worktree, note="worktree == HEAD (nothing to stash)")
    finally:
        if stashed:
            pop = git("stash", "pop")
            if pop.returncode != 0:
                print(json.dumps({
                    "error": "git stash pop failed — the diff under "
                             "test is stranded in `git stash list` "
                             "as bench-ab",
                    "stderr": pop.stderr[-500:]}), file=sys.stderr)
    ratio = None
    if isinstance(worktree.get("value"), (int, float)) and \
            isinstance(head.get("value"), (int, float)) and head["value"]:
        ratio = round(worktree["value"] / head["value"], 3)
    print(json.dumps({"metric": name, "worktree": worktree,
                      "head": head, "ratio_worktree_over_head": ratio}))


def main():
    extras = {}
    sync_rate = bench_core(extras)
    bench_serve(extras)
    bench_broadcast(extras)
    bench_pull(extras)
    bench_shuffle(extras)
    # The resnet PIPELINE bench must precede the driver's own jax TPU
    # init (its pool actor owns the chip), but it is also the most
    # expensive section — budget-gated inside. The GPT/MFU numbers in
    # bench_tpu are the headline TPU metrics and always run.
    bench_resnet(extras)
    bench_tpu(extras)
    # Envelope LAST: its 1M-queued and multi-GiB rows consume whatever
    # budget the headline sections left, scaling themselves to it.
    bench_envelope(extras)
    extras["bench_wall_s"] = round(time.monotonic() - _T0, 1)
    print(json.dumps({
        "metric": "tasks_per_second_sync",
        "value": round(sync_rate, 1),
        "unit": "tasks/s",
        "vs_baseline": round(sync_rate / BASELINE_TASKS_SYNC, 3),
        "extras": extras,
    }))


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] in ("--focus", "--ab"):
        mode, metric = argv[0], (argv[1] if len(argv) > 1 else "")
        reps = 3
        if "--reps" in argv:
            try:
                reps = int(argv[argv.index("--reps") + 1])
            except (IndexError, ValueError):
                pass
        if mode == "--focus":
            run_focus(metric, reps)
        else:
            run_ab(metric, reps)
    else:
        main()
