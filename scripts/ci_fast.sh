#!/usr/bin/env bash
# Fast CI gate (<60s): the static passes plus the dynamic zero-cost
# guards. Catches the cheap-to-catch regressions (new lint violations,
# disabled-plane overhead, gate-discipline drift) before the full
# tier-1 run. See docs/STATIC_ANALYSIS.md.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== raylint (github annotations) =="
# RAYLINT_SINCE=<rev> narrows the gate to files changed since <rev>
# (analysis still runs full-tree; only the reporting is scoped).
python -m ray_tpu.devtools.lint --format github \
    ${RAYLINT_SINCE:+--since "$RAYLINT_SINCE"}

echo "== wiretap conformance smoke (protocol DFAs under the tap) =="
# Protocol-heavy suites under RAY_TPU_WIRETAP=1: the conftest guard
# fails any test whose processes journal a nonconforming frame
# sequence, plus the tap's own unit suite (zero-work guard included).
# test_transfer drives the PULL_DIRECT/OBJ_CHUNK/OBJ_EOF stream DFA
# (including its chaos fallbacks) under the tap.
env JAX_PLATFORMS=cpu python -m pytest tests/test_wiretap.py \
    tests/test_transfer.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== serve-direct flag-off zero-work guard =="
# serve_direct_enabled=false must do ZERO serve-direct work — not
# "cheap", zero, proven by the serve_direct_ops() counter (the serve
# analogue of the direct-plane disabled guard).
env JAX_PLATFORMS=cpu python -m pytest tests/test_serve_direct.py -q \
    -m perf_smoke \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== zero-copy put path (striped reservation, lockdep+refdebug) =="
# The full put-path suite: 8-thread striped writer storm, seeded
# store.put fault rollback, flag-off zero-work and gate-bypass
# counters — the conftest guards run it under lockdep AND refdebug,
# so an ABBA cycle between the store lock and a pool stripe fails
# here, not in production.
env JAX_PLATFORMS=cpu python -m pytest tests/test_put_path.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== streaming shuffle exchange (fast tier, guard suites) =="
# The all-to-all exchange's fast tier: byte-identity vs the bulk
# two-phase path (both reducer backends), idempotent finish retry,
# working-set release, config plumbing through worker/daemon spawn.
# The conftest guard suites run this module under lockdep, refdebug
# AND wiretap; the @slow/@chaos kill/drain tier stays out of CI-fast.
env JAX_PLATFORMS=cpu python -m pytest tests/test_shuffle.py -q \
    -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== racedebug smoke (Eraser lockset detector) =="
# The dynamic half of the field-level data-race tier: the detector's
# own suite first (a seeded unprotected-sharing fixture MUST produce a
# race report with both stacks — proves the tier can still see), then
# a guarded runtime suite under RAY_TPU_RACEDEBUG=1 via the conftest
# guard (every tracked field in the hot classes must keep a non-empty
# lockset — proves the runtime is still clean). test_shuffle above
# already ran under the guard; test_direct_calls drives the
# scheduler/worker/reply-table hooks hardest.
env JAX_PLATFORMS=cpu python -m pytest tests/test_racedebug.py \
    tests/test_direct_calls.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== scale-sim smoke (stub-daemon fleet vs the event-loop head) =="
# Seconds-scale slice of the virtual-scale tier: ~50 protocol-speaking
# stub daemons attach to a real head under the wiretap, asserting
# clean DFA journals on both ends and the head thread ceiling
# (O(event loops), not O(connections)) — the thread-per-connection
# regression fails here, not at the 1,000-node tier.
env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_scale_sim.py::test_scale_smoke_wiretap -q \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== perf_smoke + lint-marked tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'perf_smoke or lint' \
    -p no:cacheprovider -p no:xdist -p no:randomly
