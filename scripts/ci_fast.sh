#!/usr/bin/env bash
# Fast CI gate (<60s): the static passes plus the dynamic zero-cost
# guards. Catches the cheap-to-catch regressions (new lint violations,
# disabled-plane overhead, gate-discipline drift) before the full
# tier-1 run. See docs/STATIC_ANALYSIS.md.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== raylint (github annotations) =="
python -m ray_tpu.devtools.lint --format github

echo "== serve-direct flag-off zero-work guard =="
# serve_direct_enabled=false must do ZERO serve-direct work — not
# "cheap", zero, proven by the serve_direct_ops() counter (the serve
# analogue of the direct-plane disabled guard).
env JAX_PLATFORMS=cpu python -m pytest tests/test_serve_direct.py -q \
    -m perf_smoke \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "== perf_smoke + lint-marked tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'perf_smoke or lint' \
    -p no:cacheprovider -p no:xdist -p no:randomly
