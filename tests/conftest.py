"""Test fixtures (reference strategy: python/ray/tests/conftest.py —
`ray_start_regular`-style local clusters; SURVEY.md §4).

Collective / mesh tests run against a virtual 8-device CPU mesh, the
reference's pattern of CPU-only collective suites mirroring the GPU ones
(util/collective/tests/single_node_cpu_tests vs distributed_gpu_tests).
"""

import os
import sys

# Tests run against the CPU backend with 8 virtual devices (SURVEY.md §4:
# the CPU mirror of the device suites). XLA_FLAGS must be set before the
# first backend init; jax.config is used for platform selection because
# some images force-register a TPU backend via sitecustomize in a way that
# overrides the JAX_PLATFORMS env var.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["XLA_FLAGS"] = _flags
os.environ["JAX_PLATFORMS"] = "cpu"
# Worker subprocesses spawned by ray_tpu set their own env; the driver-side
# jax (this process) is pinned to cpu here:
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 runs")
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection chaos runs (long; also marked "
        "slow so tier-1's `-m 'not slow'` filter excludes them)")
    config.addinivalue_line(
        "markers",
        "perf_smoke: fast, deterministic performance guards (syscall/"
        "write-count based, never wall-clock) — run in tier-1 and "
        "selectable standalone via `-m perf_smoke`")


@pytest.fixture(scope="module")
def ray_start_shared():
    """Module-shared cluster (reference: ray_start_regular_shared)."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    """Fresh cluster per test (reference: ray_start_regular)."""
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    """Test calls init() itself (reference: conftest.py:449
    shutdown_only). Shuts down BEFORE as well: a module-scoped session
    left running by an earlier test file must not leak into a test that
    needs its own init() (e.g. a custom object_store_memory)."""
    ray_tpu.shutdown()
    yield
    ray_tpu.shutdown()
