"""Test fixtures (reference strategy: python/ray/tests/conftest.py —
`ray_start_regular`-style local clusters; SURVEY.md §4).

Collective / mesh tests run against a virtual 8-device CPU mesh, the
reference's pattern of CPU-only collective suites mirroring the GPU ones
(util/collective/tests/single_node_cpu_tests vs distributed_gpu_tests).
"""

import os
import sys

# Tests run against the CPU backend with 8 virtual devices (SURVEY.md §4:
# the CPU mirror of the device suites). XLA_FLAGS must be set before the
# first backend init; jax.config is used for platform selection because
# some images force-register a TPU backend via sitecustomize in a way that
# overrides the JAX_PLATFORMS env var.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["XLA_FLAGS"] = _flags
os.environ["JAX_PLATFORMS"] = "cpu"
# Worker subprocesses spawned by ray_tpu set their own env; the driver-side
# jax (this process) is pinned to cpu here:
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 runs")
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection chaos runs (long; also marked "
        "slow so tier-1's `-m 'not slow'` filter excludes them)")
    config.addinivalue_line(
        "markers",
        "perf_smoke: fast, deterministic performance guards (syscall/"
        "write-count based, never wall-clock) — run in tier-1 and "
        "selectable standalone via `-m perf_smoke`")
    config.addinivalue_line(
        "markers",
        "lint: project-invariant static-analysis suite "
        "(ray_tpu/devtools/lint) run against the live tree in tier-1; "
        "selectable standalone via `-m lint`")


# Suites that run under the dynamic lock-order tracker
# (_private/lockdep.py): the transport-framing tier exercises the
# writer/executor/gate locks directly, and the chaos tier drives the
# whole control plane through failure paths — both must come out with
# ZERO potential-ABBA cycles. Assertion per test so a report is
# attributable to the test that produced it.
_LOCKDEP_SUITES = {"test_transport_framing", "test_fault_injection",
                   "test_direct_calls", "test_cross_plane_ordering",
                   "test_serve_direct", "test_put_path", "test_shuffle"}


@pytest.fixture(autouse=True)
def _lockdep_guard(request, tmp_path_factory):
    name = getattr(request.module, "__name__", "")
    if name.rpartition(".")[2] not in _LOCKDEP_SUITES:
        yield
        return
    from ray_tpu._private import lockdep
    lockdep.reset()
    prev = lockdep.enabled
    # Spill dir: cycles recorded in SPAWNED daemons/workers (which
    # inherit RAY_TPU_LOCKDEP=1) are process-local and die with them —
    # every process appends cycles here at record time, so the
    # assertion below covers the whole process tree, not just the head.
    dump_dir = str(tmp_path_factory.mktemp("lockdep"))
    prev_dir = os.environ.get("RAY_TPU_LOCKDEP_DIR")
    os.environ["RAY_TPU_LOCKDEP_DIR"] = dump_dir
    lockdep.configure(True)
    try:
        yield
        cycles = list(lockdep.cycle_reports())
        seen = {(tuple(c["cycle"]), c.get("pid")) for c in cycles}
        for rep in lockdep.collect_dumped_cycles(dump_dir):
            key = (tuple(rep["cycle"]), rep.get("pid"))
            if key not in seen:
                seen.add(key)
                cycles.append(rep)
        if cycles:
            child = [c for c in cycles if c.get("pid") != os.getpid()]
            pytest.fail(
                f"lockdep: {len(cycles)} potential ABBA deadlock(s) "
                f"recorded during this test ({len(child)} in child "
                f"processes):\n" + lockdep.format_reports()
                + "".join(f"\n[child pid {c.get('pid')}] cycle "
                          f"{' -> '.join(c['cycle'])}" for c in child))
    finally:
        lockdep.configure(prev)
        if prev_dir is None:
            os.environ.pop("RAY_TPU_LOCKDEP_DIR", None)
        else:
            os.environ["RAY_TPU_LOCKDEP_DIR"] = prev_dir


# Suites that run under the refcount-conservation shadow ledger
# (_private/refdebug.py): the direct-call and cross-plane tiers
# exercise the buffered-accounting surface (parks, barriers, borrows,
# escapes) and the chaos tier kills processes mid-accounting — every
# test must replay to a clean conservation report. Per-test journal
# dir so a violation is attributable to the test that produced it
# (these suites all build per-test clusters).
_REFDEBUG_SUITES = {"test_direct_calls", "test_cross_plane_ordering",
                    "test_fault_injection", "test_drain",
                    "test_serve_direct", "test_transfer",
                    "test_put_path", "test_shuffle"}


@pytest.fixture(autouse=True)
def _refdebug_guard(request, tmp_path_factory):
    name = getattr(request.module, "__name__", "")
    if name.rpartition(".")[2] not in _REFDEBUG_SUITES:
        yield
        return
    from ray_tpu._private import refdebug
    refdebug.reset()
    prev = refdebug.enabled
    # Journal dir: every process of the run (head, daemons, workers —
    # which inherit RAY_TPU_REFDEBUG=1) appends its refcount events
    # here at record time, SIGKILL-safe; the checker replays the merged
    # journals on teardown.
    dump_dir = str(tmp_path_factory.mktemp("refdebug"))
    prev_dir = os.environ.get("RAY_TPU_REFDEBUG_DIR")
    os.environ["RAY_TPU_REFDEBUG_DIR"] = dump_dir
    refdebug.configure(True)
    try:
        yield
        refdebug.reset()  # close our journal handle before replaying
        violations = refdebug.check_journals(dump_dir)
        if violations:
            pytest.fail(
                f"refdebug: {len(violations)} refcount-conservation "
                f"violation(s) recorded during this test:\n"
                + refdebug.format_report(violations))
    finally:
        refdebug.configure(prev)
        if prev_dir is None:
            os.environ.pop("RAY_TPU_REFDEBUG_DIR", None)
        else:
            os.environ["RAY_TPU_REFDEBUG_DIR"] = prev_dir


# Suites that run under the wire-protocol conformance tap
# (_private/wiretap.py): the protocol-heavy tiers replay every frame
# crossing a recv mux through the session DFAs of
# devtools/lint/protocol_model.py — the dynamic half of the
# protocol-order/payload-schema static passes. Per-test journal dir so
# a nonconforming sequence is attributable to the test that produced
# it (every process of the run appends violations at record time,
# SIGKILL-safe).
_WIRETAP_SUITES = {"test_direct_calls", "test_cross_plane_ordering",
                   "test_serve_direct", "test_transfer", "test_shuffle"}


@pytest.fixture(autouse=True)
def _wiretap_guard(request, tmp_path_factory):
    name = getattr(request.module, "__name__", "")
    if name.rpartition(".")[2] not in _WIRETAP_SUITES:
        yield
        return
    from ray_tpu._private import wiretap
    wiretap.reset()
    prev = wiretap.enabled
    dump_dir = str(tmp_path_factory.mktemp("wiretap"))
    prev_dir = os.environ.get("RAY_TPU_WIRETAP_DIR")
    os.environ["RAY_TPU_WIRETAP_DIR"] = dump_dir
    wiretap.configure(True)
    try:
        yield
        wiretap.reset()  # close our journal handle before replaying
        violations = wiretap.collect_violations(dump_dir)
        if violations:
            pytest.fail(
                f"wiretap: {len(violations)} wire-protocol "
                f"violation(s) recorded during this test:\n"
                + wiretap.format_report(violations))
    finally:
        wiretap.configure(prev)
        if prev_dir is None:
            os.environ.pop("RAY_TPU_WIRETAP_DIR", None)
        else:
            os.environ["RAY_TPU_WIRETAP_DIR"] = prev_dir


# Suites that run under the Eraser-style lockset race detector
# (_private/racedebug.py): the direct-call, cross-plane, shuffle and
# chaos tiers drive the hot concurrent classes (scheduler queue,
# writer queues, reply tables, actor queues) from many threads at
# once — every tracked field must keep a non-empty candidate lockset
# for the whole test. Per-test spill dir so a race is attributable to
# the test that produced it (spawned daemons/workers inherit
# RAY_TPU_RACEDEBUG=1 and append reports at record time, SIGKILL-safe).
_RACEDEBUG_SUITES = {"test_direct_calls", "test_cross_plane_ordering",
                     "test_shuffle", "test_fault_injection"}


@pytest.fixture(autouse=True)
def _racedebug_guard(request, tmp_path_factory):
    name = getattr(request.module, "__name__", "")
    if name.rpartition(".")[2] not in _RACEDEBUG_SUITES:
        yield
        return
    from ray_tpu._private import racedebug
    racedebug.reset()
    prev = racedebug.enabled
    dump_dir = str(tmp_path_factory.mktemp("racedebug"))
    prev_dir = os.environ.get("RAY_TPU_RACEDEBUG_DIR")
    os.environ["RAY_TPU_RACEDEBUG_DIR"] = dump_dir
    racedebug.configure(True)
    try:
        yield
        races = racedebug.race_reports()
        seen = {(r["owner"], r["field"], r.get("pid")) for r in races}
        for rep in racedebug.collect_dumped_races(dump_dir):
            key = (rep["owner"], rep["field"], rep.get("pid"))
            if key not in seen:
                seen.add(key)
                races.append(rep)
        if races:
            child = [r for r in races if r.get("pid") != os.getpid()]
            pytest.fail(
                f"racedebug: {len(races)} potential data race(s) "
                f"recorded during this test ({len(child)} in child "
                f"processes):\n" + racedebug.format_reports()
                + "".join(f"\n[child pid {r.get('pid')}] "
                          f"{r['owner']}.{r['field']}" for r in child))
    finally:
        # configure(prev) restores the racedebug flag only; lockdep —
        # which racedebug.configure(True) switched on as its lockset
        # source — is left alone (the lockdep guard owns that flag).
        racedebug.configure(prev)
        if prev_dir is None:
            os.environ.pop("RAY_TPU_RACEDEBUG_DIR", None)
        else:
            os.environ["RAY_TPU_RACEDEBUG_DIR"] = prev_dir


@pytest.fixture(scope="module")
def ray_start_shared():
    """Module-shared cluster (reference: ray_start_regular_shared)."""
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    """Fresh cluster per test (reference: ray_start_regular)."""
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    """Test calls init() itself (reference: conftest.py:449
    shutdown_only). Shuts down BEFORE as well: a module-scoped session
    left running by an earlier test file must not leak into a test that
    needs its own init() (e.g. a custom object_store_memory)."""
    ray_tpu.shutdown()
    yield
    ray_tpu.shutdown()
