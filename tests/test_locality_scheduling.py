"""Locality- and utilization-aware DEFAULT (hybrid) scheduling.

Reference semantics under test:
- LocalityAwareLeasePolicy picks the node holding the most bytes of the
  task's args (src/ray/core_worker/lease_policy.cc:38-58).
- The hybrid policy prefers the local/preferred node while its
  critical-resource utilization is below the spread threshold, then
  spreads to the least-utilized node
  (src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc:48-160).
Reference tests: python/ray/tests/test_scheduling.py (locality-aware
leases over a ray_start_cluster).
"""

import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture(scope="module")
def locality_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    a = cluster.add_node(num_cpus=2, daemon=True)
    b = cluster.add_node(num_cpus=2, daemon=True)
    yield cluster, a, b
    try:
        cluster.shutdown()
    except Exception:
        pass


@ray.remote
def where():
    return ray.get_runtime_context().get_node_id()


@ray.remote
def make_block(mb):
    return np.zeros(mb << 20, dtype=np.uint8)


@ray.remote
def consume(x):
    assert x.nbytes > 0
    return ray.get_runtime_context().get_node_id()


def _on(node):
    return NodeAffinitySchedulingStrategy(node_id=node.node_id, soft=False)


def test_task_follows_its_input_block(locality_cluster):
    cluster, a, b = locality_cluster
    # Produce a 4 MiB block ON node a; the DEFAULT-strategy consumer
    # must be scheduled onto a (where its arg bytes live), not onto the
    # idle head — that is the locality-aware lease decision.
    ref = make_block.options(scheduling_strategy=_on(a)).remote(4)
    ray.wait([ref])
    got = [ray.get(consume.remote(ref)) for _ in range(3)]
    assert got == [a.node_id] * 3, got


def test_larger_arg_wins_locality(locality_cluster):
    cluster, a, b = locality_cluster
    small = make_block.options(scheduling_strategy=_on(a)).remote(1)
    big = make_block.options(scheduling_strategy=_on(b)).remote(8)
    ray.wait([small, big], num_returns=2)

    @ray.remote
    def consume2(x, y):
        return ray.get_runtime_context().get_node_id()

    # b holds 8 MiB of the args, a holds 1 MiB: b must win the lease.
    got = ray.get(consume2.remote(small, big))
    assert got == b.node_id


def test_inline_args_do_not_pin(locality_cluster):
    cluster, a, b = locality_cluster
    head_hex = cluster.head_node.node_id
    # Tiny (inline) args carry no location: DEFAULT keeps preferring
    # the head like before.
    @ray.remote
    def add(x, y):
        return ray.get_runtime_context().get_node_id()

    got = [ray.get(add.remote(1, 2)) for _ in range(3)]
    assert got.count(head_hex) >= 2, got


def test_spread_past_saturated_head_to_least_utilized(locality_cluster):
    cluster, a, b = locality_cluster
    head_hex = cluster.head_node.node_id

    @ray.remote
    def sleeper(t):
        time.sleep(t)
        return 1

    # Saturate the head (2/2 CPUs) and half-load a (1/2 CPUs).
    busy = [sleeper.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=head_hex, soft=False)).remote(3.0) for _ in range(2)]
    half = sleeper.options(scheduling_strategy=_on(a)).remote(3.0)
    time.sleep(0.5)  # let them start running
    # DEFAULT task with no locality: head is at utilization 1.0 (past
    # the spread threshold), so it must land on the LEAST utilized
    # node — b (0/2), not a (1/2).
    got = ray.get(where.remote())
    assert got == b.node_id, got
    ray.get(busy + [half])
