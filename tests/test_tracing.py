"""End-to-end request tracing plane (PR 7): spans ride TASK_EVENTS
frames into bounded per-trace rings, trace context propagates across
BOTH call planes (head-routed and direct worker<->worker — traced calls
keep the compact wire form), in/out of the serve proxy via W3C
``traceparent`` headers, and `export_chrome_trace` merges spans with the
task timeline on the pid=node / tid=worker layout. Reference strategy:
python/ray/tests/test_tracing.py over util/tracing/tracing_helper.py."""
import os
import time

import pytest

import ray_tpu
from ray_tpu._private import protocol as P
from ray_tpu._private import telemetry
from ray_tpu.util import tracing


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def tracing_on():
    tracing.enable()
    yield
    tracing.disable()
    os.environ.pop("RAY_TPU_TRACING", None)


def _poll_trace(trace_id, min_spans, timeout=20.0):
    deadline = time.monotonic() + timeout
    tree = {}
    while time.monotonic() < deadline:
        tree = tracing.get_trace(trace_id)
        if tree.get("span_count", 0) >= min_spans:
            return tree
        time.sleep(0.25)
    return tree


def _tree_names(tree):
    counts = {}

    def walk(node):
        counts[node["name"]] = counts.get(node["name"], 0) + 1
        for c in node["children"]:
            walk(c)

    for r in tree.get("roots", ()):
        walk(r)
    return counts


# ---------------------------------------------------------------------------
# pure units
# ---------------------------------------------------------------------------
def test_traceparent_helpers():
    tp = tracing.format_traceparent("ab" * 16, "cd" * 8)
    ctx = tracing.parse_traceparent(tp)
    assert ctx == {"trace_id": "ab" * 16, "parent_span_id": "cd" * 8}
    for bad in (None, "", "garbage", "00-zz-cd-01",
                "00-" + "a" * 31 + "-" + "c" * 16 + "-01"):
        assert tracing.parse_traceparent(bad) is None


def test_build_trace_tree_and_critical_path():
    t = "t" * 32
    spans = [
        {"trace_id": t, "span_id": "a", "parent_span_id": None,
         "name": "root", "start": 0.0, "end": 10.0},
        {"trace_id": t, "span_id": "b", "parent_span_id": "a",
         "name": "fast", "start": 1.0, "end": 2.0},
        {"trace_id": t, "span_id": "c", "parent_span_id": "a",
         "name": "slow", "start": 1.0, "end": 9.0},
        {"trace_id": t, "span_id": "d", "parent_span_id": "c",
         "name": "leaf", "start": 2.0, "end": 8.5},
        # duplicate span id (retry replay) must not duplicate a node
        {"trace_id": t, "span_id": "d", "parent_span_id": "c",
         "name": "leaf", "start": 2.0, "end": 8.5},
    ]
    tree = tracing.build_trace(spans)
    assert tree["span_count"] == 4
    assert len(tree["roots"]) == 1
    assert tree["duration_s"] == 10.0
    crit = [s["name"] for s in tree["critical_path"]]
    assert crit == ["root", "slow", "leaf"]
    assert tracing.format_trace(tree)  # renders without error


def test_compact_wire_carries_trace_ctx():
    """Traced no-arg direct calls keep the compact wire form: the trace
    context rides as a tail slot instead of demoting the call to the
    full-spec pickle (the old behavior the tentpole removes)."""
    from ray_tpu._private.direct import DirectPlane
    from ray_tpu._private.ids import ActorID, TaskID, object_id_for_return

    sent = []

    class _Writer:
        def send_message(self, msg_type, payload):
            sent.append((msg_type, payload))

    class _Chan:
        writer = _Writer()

    tid = TaskID.from_random()
    ctx = {"trace_id": "ab" * 16, "parent_span_id": "cd" * 8}
    spec = P.TaskSpec(
        task_id=tid, fn_id="A.m", fn_blob=None,
        return_ids=[object_id_for_return(tid, 0)], num_returns=1,
        name="A.m", actor_id=ActorID.from_random(), method_name="m",
        caller_id=b"w" * 16, caller_seq=3, seq_preds=(), trace_ctx=ctx)
    DirectPlane._send_call(None, _Chan(), spec)
    msg_type, payload = sent[0]
    assert msg_type == P.ACTOR_CALL
    assert "c" in payload and "spec" not in payload  # compact form held
    rebuilt = DirectPlane._wire_spec(payload)
    assert rebuilt.trace_ctx == ctx
    assert rebuilt.caller_seq == 3
    assert rebuilt.task_id.binary() == tid.binary()
    # untraced calls stay compact too, with a None tail slot
    spec.trace_ctx = None
    DirectPlane._send_call(None, _Chan(), spec)
    assert DirectPlane._wire_spec(sent[1][1]).trace_ctx is None


# ---------------------------------------------------------------------------
# propagation across the planes
# ---------------------------------------------------------------------------
def test_trace_tree_across_head_plane(tracing_on):
    @ray_tpu.remote
    def child(x):
        return x * 2

    @ray_tpu.remote
    def parent(x):
        return ray_tpu.get(child.remote(x)) + 1

    with tracing.span("root") as root_sid:
        assert ray_tpu.get(parent.remote(5)) == 11
        ctx = tracing.current_context()
    assert root_sid and ctx["trace_id"]
    tree = _poll_trace(ctx["trace_id"], 5)
    names = _tree_names(tree)
    assert names.get("root") == 1
    assert names.get("task:parent") == 1
    assert names.get("task:child") == 1
    assert len(tree["roots"]) == 1  # one causally-linked tree


def test_trace_tree_across_direct_channel(tracing_on):
    """Worker->worker actor calls on the brokered channel carry the
    context (compact tail slot) and their exec spans join the tree."""
    from ray_tpu._private.config import ray_config
    assert ray_config.direct_calls_enabled  # the plane under test

    @ray_tpu.remote
    class Callee:
        def nop(self):
            return 1

    @ray_tpu.remote
    class Caller:
        def __init__(self, callee):
            self.callee = callee

        def drive(self, n):
            return sum(ray_tpu.get(
                [self.callee.nop.remote() for _ in range(n)]))

    callee = Callee.remote()
    caller = Caller.remote(callee)
    with tracing.span("direct-root"):
        assert ray_tpu.get(caller.drive.remote(4)) == 4
        ctx = tracing.current_context()
    # direct-root + submit/task drive + 4x (submit + task nop)
    tree = _poll_trace(ctx["trace_id"], 11)
    names = _tree_names(tree)
    assert names.get("direct-root") == 1
    assert any(k.startswith("task:") and k.endswith("Caller.drive")
               for k in names)
    nop_tasks = [k for k in names
                 if k.startswith("task:") and k.endswith("Callee.nop")]
    assert nop_tasks and names[nop_tasks[0]] == 4
    assert len(tree["roots"]) == 1


def test_traced_streaming_generator(tracing_on):
    """Trace context flows through streaming calls: the generator's
    exec span joins the tree (GEN_ITEM terminal registration keeps the
    stream's accounting; tracing must not break it)."""
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield i

    @ray_tpu.remote
    class Consumer:
        def __init__(self, g):
            self.g = g

        def consume(self, n):
            got = 0
            for _ref in self.g.stream.options(
                    num_returns="streaming").remote(n):
                got += 1
            return got

    g = Gen.remote()
    c = Consumer.remote(g)
    with tracing.span("stream-root"):
        assert ray_tpu.get(c.consume.remote(5)) == 5
        ctx = tracing.current_context()
    tree = _poll_trace(ctx["trace_id"], 5)
    names = _tree_names(tree)
    assert names.get("stream-root") == 1
    assert any(k.endswith("Gen.stream") and k.startswith("task:")
               for k in names)
    assert len(tree["roots"]) == 1


def test_put_span_joins_trace(tracing_on):
    with tracing.span("put-root"):
        ref = ray_tpu.put([1, 2, 3])
        ctx = tracing.current_context()
    assert ray_tpu.get(ref) == [1, 2, 3]
    tree = _poll_trace(ctx["trace_id"], 2, timeout=5.0)
    assert _tree_names(tree).get("put") == 1


def test_chrome_export_merge_shape(tracing_on):
    @ray_tpu.remote
    def chrome_probe(x):
        return x

    with tracing.span("chrome-root"):
        ray_tpu.get(chrome_probe.remote(1))
        ctx = tracing.current_context()
    _poll_trace(ctx["trace_id"], 3)
    events = tracing.export_chrome_trace(trace_id=ctx["trace_id"])
    spans = [e for e in events if e.get("cat") == "span"]
    assert spans
    from ray_tpu._private.state import get_node
    head_hex = get_node().node_id.hex()
    for e in spans:
        assert e["ph"] == "X" and e["dur"] >= 0
        assert e["args"]["trace_id"] == ctx["trace_id"]
    # layout contract: pid = node (head here), tid = worker or driver
    exec_spans = [e for e in spans if e["name"] == "task:chrome_probe"]
    assert exec_spans
    assert exec_spans[0]["pid"] == head_hex[:8]
    assert exec_spans[0]["tid"] != "driver"
    root = [e for e in spans if e["name"] == "chrome-root"][0]
    assert root["tid"] == "driver" and root["pid"] == head_hex[:8]
    # task-timeline events share the same pid space (merged layout)
    tasks = [e for e in events if e.get("cat") == "task"
             and e["name"] == "chrome_probe"]
    assert tasks and tasks[0]["pid"] == head_hex[:8]


def test_serve_traceparent_roundtrip(tracing_on):
    """W3C traceparent in -> proxy span + replica dispatch under the
    client's trace id -> traceparent echoed on the response."""
    import http.client

    from ray_tpu import serve

    serve.start()
    try:
        @serve.deployment
        def traced_hello(request):
            return "hi"

        serve.run(traced_hello.bind(), name="traced_app",
                  route_prefix="/traced-hello")
        host, port = serve.proxy_address().replace(
            "http://", "").split(":")
        conn = http.client.HTTPConnection(host, int(port))
        trace_id = "ab" * 16
        tp_in = tracing.format_traceparent(trace_id, "cd" * 8)
        conn.request("POST", "/traced-hello", body=b"{}",
                     headers={"traceparent": tp_in})
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200 and body == b"hi"
        tp_out = resp.getheader("traceparent")
        assert tp_out is not None
        out_ctx = tracing.parse_traceparent(tp_out)
        # same trace, NEW span id (the proxy's serve.request span)
        assert out_ctx["trace_id"] == trace_id
        assert out_ctx["parent_span_id"] != "cd" * 8
        tree = _poll_trace(trace_id, 3)
        names = _tree_names(tree)
        assert names.get("serve.request") == 1
        assert any(k.startswith("task:") and "handle_request" in k
                   for k in names)
    finally:
        serve.shutdown()


def test_head_self_metrics_in_exposition():
    """Acceptance: head self-metrics (msgs by type, loop queue depths,
    handler pool, writer queue bytes) appear in the federated /metrics
    exposition with node tags."""
    from ray_tpu._private.state import get_node

    @ray_tpu.remote
    def self_metrics_probe():
        return 1

    ray_tpu.get([self_metrics_probe.remote() for _ in range(8)])
    node = get_node()
    head_hex = node.node_id.hex()
    text = telemetry.federated_prometheus_text(node)
    assert (f'head_ingest_messages{{msg_type="task_done",'
            f'node_id="{head_hex}"}}') in text
    assert f'head_handler_pool_queue_depth{{node_id="{head_hex}"}}' \
        in text
    assert f'head_handler_pool_active{{node_id="{head_hex}"}}' in text
    assert f'head_writer_queue_bytes{{node_id="{head_hex}"}}' in text


# ---------------------------------------------------------------------------
# destructive tests (re-init the shared runtime); keep them LAST
# ---------------------------------------------------------------------------
def test_idle_drain_flushes_trailing_direct_events():
    """PR 6 residual deviation, closed: an idle callee's FINISHED
    events for direct calls no longer trail until the 256-event
    threshold or its next head-bound frame — the TELEMETRY_DRAIN nudge
    riding the heartbeat cadence flushes them (no new threads)."""
    from ray_tpu._private.config import ray_config
    from ray_tpu.util import state as state_api

    ray_tpu.shutdown()
    prev_hb = float(ray_config.node_heartbeat_s)
    os.environ["RAY_TPU_NODE_HEARTBEAT_S"] = "0.25"
    ray_config.set("node_heartbeat_s", 0.25)
    try:
        ray_tpu.init(num_cpus=4)

        @ray_tpu.remote
        class DrainCallee:
            def nop(self):
                return 1

        @ray_tpu.remote
        class DrainCaller:
            def __init__(self, callee):
                self.callee = callee

            def drive(self, n):
                return sum(ray_tpu.get(
                    [self.callee.nop.remote() for _ in range(n)]))

        callee = DrainCallee.remote()
        caller = DrainCaller.remote(callee)
        assert ray_tpu.get(caller.drive.remote(3)) == 3
        # The callee is now idle with its nop FINISHED events buffered
        # (far under the 256 threshold). Nothing else talks to the
        # head from it — the drain nudge must deliver them.
        deadline = time.monotonic() + 10
        rows = []
        while time.monotonic() < deadline:
            rows = [t for t in state_api.list_tasks(limit=10000)
                    if t["name"].endswith("DrainCallee.nop")
                    and t["state"] == "FINISHED"]
            if len(rows) == 3:
                break
            time.sleep(0.2)
        assert len(rows) == 3, rows
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_NODE_HEARTBEAT_S", None)
        ray_config.set("node_heartbeat_s", prev_hb)
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)


def test_sigkill_mid_trace_spans_survive_exactly_once():
    """SIGKILL mid-traced-task: spans that reached the head survive,
    drop accounting stays exact (integers, no negatives), and the
    retry after the reconcile does not duplicate spans in the tree —
    the killed attempt's unflushed span dies with the worker, the
    retry records exactly one exec span."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, prestart_workers=0, fault_config={
        "seed": 5,
        "rules": [{"site": "worker.exec", "action": "kill", "at": [1]}]})
    tracing.enable()
    try:
        @ray_tpu.remote(max_retries=0)
        def pre_kill(x):
            return x

        @ray_tpu.remote(max_retries=1)
        def doomed():
            return 1

        with tracing.span("kill-root"):
            # First exec survives (kill fires at exec index 1): its
            # span must reach the head and stay there.
            assert ray_tpu.get(pre_kill.remote(7), timeout=60) == 7
            # The kill lands mid-exec of `doomed`; the head's retry
            # delivers the result from a fresh worker.
            assert ray_tpu.get(doomed.remote(), timeout=120) == 1
            ctx = tracing.current_context()
        tree = _poll_trace(ctx["trace_id"], 4)
        names = _tree_names(tree)
        assert names.get("kill-root") == 1
        assert names.get("task:pre_kill") == 1  # survived the crash
        # Exactly ONE exec span for the killed-then-retried task: the
        # killed attempt's span never flushed, the retry's did.
        assert names.get("task:doomed") == 1, names
        # No span id appears twice after the retry/reconcile churn.
        seen = set()

        def walk(n):
            assert n["span_id"] not in seen
            seen.add(n["span_id"])
            for c in n["children"]:
                walk(c)

        for r in tree["roots"]:
            walk(r)
        from ray_tpu._private.state import get_node
        drops = get_node().gcs.telemetry.span_drop_counts()
        assert all(isinstance(v, int) and v >= 0 for v in drops.values())
    finally:
        tracing.disable()
        os.environ.pop("RAY_TPU_TRACING", None)
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)


def test_multinode_serve_fanout_single_tree():
    """Acceptance criterion: on a 2-node daemon cluster, a serve
    request that fans out over the direct plane exports as ONE
    causally-linked cross-node tree (proxy -> replica -> nested actor
    tasks), pid=node / tid=worker in the chrome merge."""
    import urllib.request

    from ray_tpu import serve
    from ray_tpu._private.config import ray_config
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    prev_hb = float(ray_config.node_heartbeat_s)
    os.environ["RAY_TPU_NODE_HEARTBEAT_S"] = "0.25"
    ray_config.set("node_heartbeat_s", 0.25)
    tracing.enable()  # daemons/workers inherit via RAY_TPU_TRACING
    cluster = None
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
        a = cluster.add_node(num_cpus=2, daemon=True)
        b = cluster.add_node(num_cpus=2, daemon=True)

        @ray_tpu.remote
        class Fanout:
            def part(self, i):
                return i * i

        fan = Fanout.remote()

        @serve.deployment(max_ongoing_requests=8)
        class TracedApi:
            def __init__(self, fan):
                self._fan = fan

            def __call__(self, request):
                import ray_tpu as _r
                return {"n": sum(_r.get(
                    [self._fan.part.remote(i) for i in range(3)]))}

        serve.run(TracedApi.bind(fan), name="traced_fan",
                  route_prefix="/fan")
        # Hit a DAEMON node's proxy so the request span originates on a
        # non-head node (cross-node by construction).
        deadline = time.monotonic() + 120
        addrs = {}
        while time.monotonic() < deadline:
            addrs = serve.proxy_addresses()
            if a.node_id in addrs:
                break
            time.sleep(0.5)
        assert a.node_id in addrs, addrs
        trace_id = os.urandom(16).hex()
        req = urllib.request.Request(
            f"{addrs[a.node_id]}/fan", data=b"{}",
            headers={"traceparent": tracing.format_traceparent(
                trace_id, "cd" * 8)})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            assert b'"n": 5' in r.read()
        # serve.request + submit/task handle_request + 3x submit/task
        # Fanout.part = 9 spans minimum.
        tree = _poll_trace(trace_id, 9, timeout=30.0)
        names = _tree_names(tree)
        assert names.get("serve.request") == 1, names
        assert any(k.startswith("task:") and "handle_request" in k
                   for k in names), names
        parts = [k for k in names
                 if k.startswith("task:") and k.endswith("Fanout.part")]
        assert parts and names[parts[0]] == 3, names
        assert len(tree["roots"]) == 1  # ONE causally-linked tree
        assert len(tree["node_ids"]) >= 2, tree["node_ids"]  # cross-node
        # chrome merge: the trace's spans land under >= 2 node rows.
        events = tracing.export_chrome_trace(trace_id=trace_id)
        pids = {e["pid"] for e in events if e.get("cat") == "span"}
        assert len(pids) >= 2, pids
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        tracing.disable()
        os.environ.pop("RAY_TPU_TRACING", None)
        try:
            if cluster is not None:
                cluster.shutdown()
        except Exception:
            pass
        os.environ.pop("RAY_TPU_NODE_HEARTBEAT_S", None)
        ray_config.set("node_heartbeat_s", prev_hb)
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)


@pytest.mark.perf_smoke
def test_tracing_off_hot_path_zero_work():
    """Counter-based guard (wall-clock-free): with tracing OFF, task
    batches on BOTH planes — head-routed plain tasks and direct
    worker<->worker actor calls — invoke ZERO tracing helpers in the
    driver and land ZERO spans in the head store (the worker-side
    proxy for zero tracing work: any span recorded would surface
    there via the TASK_EVENTS piggyback or the idle drain)."""
    ray_tpu.shutdown()
    assert not tracing.is_enabled()
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote
        def head_probe(x):
            return x

        @ray_tpu.remote
        class ZCallee:
            def nop(self):
                return 1

        @ray_tpu.remote
        class ZCaller:
            def __init__(self, callee):
                self.callee = callee

            def drive(self, n):
                return sum(ray_tpu.get(
                    [self.callee.nop.remote() for _ in range(n)]))

        callee = ZCallee.remote()
        caller = ZCaller.remote(callee)
        ray_tpu.get(caller.drive.remote(2))  # warm the channel
        ray_tpu.get([head_probe.remote(i) for i in range(8)])
        tracing.drain_spans()  # clear residue from earlier enabled tests
        ops_before = tracing.trace_ops()
        ray_tpu.get([head_probe.remote(i) for i in range(16)])
        assert ray_tpu.get(caller.drive.remote(16)) == 16
        assert tracing.trace_ops() == ops_before
        assert len(tracing._buffer) == 0
        from ray_tpu._private.state import get_node
        tstore = get_node().gcs.telemetry
        # settle: give any (erroneous) span flush time to arrive
        time.sleep(0.5)
        assert tstore.spans_ingested == 0
        assert tstore.spans() == []
    finally:
        ray_tpu.shutdown()
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
