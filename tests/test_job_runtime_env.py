"""Job submission + runtime env tests (reference strategy:
dashboard/modules/job/tests/test_job_manager.py,
python/ray/tests/test_runtime_env*.py)."""
import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu.job import (FAILED, JobSubmissionClient, STOPPED, SUCCEEDED)


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# -- runtime envs -----------------------------------------------------------
def test_runtime_env_env_vars():
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "tpu42"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_flag.remote()) == "tpu42"

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    # env-var workers are segregated from the generic pool
    assert ray_tpu.get(read_plain.remote()) is None


def test_runtime_env_working_dir_and_py_modules(tmp_path):
    pkg = tmp_path / "vendored_mod"
    pkg.mkdir()
    (pkg / "vendored_lib_xyz.py").write_text("VALUE = 1234\n")
    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "data.txt").write_text("hello-wd")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(pkg)]})
    def use_env():
        import vendored_lib_xyz
        with open("data.txt") as f:
            return vendored_lib_xyz.VALUE, f.read()

    assert ray_tpu.get(use_env.remote()) == (1234, "hello-wd")


def test_runtime_env_on_actor():
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_ENV": "yes"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote()) == "yes"


def test_runtime_env_validation():
    # pip/uv are SUPPORTED (offline venvs); conda needs its tool (r3);
    # container stays gated.
    import shutil
    if not (shutil.which("conda") or shutil.which("mamba")
            or shutil.which("micromamba")):
        with pytest.raises(ValueError, match="conda|gates off"):
            @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["x"]}})
            def f():
                pass
            f.remote()
    with pytest.raises(ValueError, match="Unknown runtime_env"):
        @ray_tpu.remote(runtime_env={"bogus_field": 1})
        def g():
            pass
        g.remote()
    with pytest.raises(ValueError, match="does not exist"):
        @ray_tpu.remote(runtime_env={"working_dir": "/nonexistent_xyz"})
        def h():
            pass
        h.remote()


# -- jobs -------------------------------------------------------------------
def test_job_submit_success_and_logs():
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job says hi')\"")
    assert client.wait_until_finish(job_id, 60) == SUCCEEDED
    assert "job says hi" in client.get_job_logs(job_id)
    info = client.get_job_info(job_id)
    assert info["return_code"] == 0
    assert job_id in [j["job_id"] for j in client.list_jobs()]


def test_job_failure():
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import sys; sys.exit(3)\"")
    assert client.wait_until_finish(job_id, 60) == FAILED
    assert client.get_job_info(job_id)["return_code"] == 3


def test_job_env_vars_and_stop():
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \""
                   "import os,time; print(os.environ['JOB_VAR']); "
                   "time.sleep(60)\"",
        runtime_env={"env_vars": {"JOB_VAR": "injected"}})
    deadline = time.time() + 30
    while time.time() < deadline:
        if "injected" in client.get_job_logs(job_id):
            break
        time.sleep(0.2)
    assert "injected" in client.get_job_logs(job_id)
    assert client.stop_job(job_id)
    assert client.wait_until_finish(job_id, 30) in (STOPPED, FAILED)


def test_job_delete_and_duplicate_id():
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c pass", submission_id="raysubmit_dup")
    client.wait_until_finish(job_id, 60)
    with pytest.raises(ValueError, match="already exists"):
        client.submit_job(entrypoint="true", submission_id="raysubmit_dup")
    assert client.delete_job(job_id)
    with pytest.raises(ValueError, match="No job"):
        client.get_job_status(job_id)


class TestPipRuntimeEnv:
    """pip runtime envs (reference: _private/runtime_env/pip.py): a venv
    per requirements-hash, workers run its interpreter. Exercised fully
    OFFLINE with a wheel built on the spot — the egress-less mirror of
    the reference's PyPI path."""

    @pytest.fixture(scope="class")
    def wheel(self, tmp_path_factory):
        import subprocess
        import sys
        root = tmp_path_factory.mktemp("pkg")
        (root / "src" / "tinypkg").mkdir(parents=True)
        (root / "src" / "tinypkg" / "__init__.py").write_text(
            "def greet():\n    return 'hi-from-tinypkg'\n")
        (root / "pyproject.toml").write_text(
            '[project]\nname = "tinypkg"\nversion = "1.0"\n\n'
            '[build-system]\nrequires = ["setuptools"]\n'
            'build-backend = "setuptools.build_meta"\n\n'
            '[tool.setuptools.packages.find]\nwhere = ["src"]\n')
        subprocess.run(
            [sys.executable, "-m", "pip", "wheel", str(root),
             "--no-build-isolation", "--no-deps", "-w",
             str(root / "dist"), "-q"],
            check=True, capture_output=True, timeout=180)
        (whl,) = (root / "dist").glob("*.whl")
        return str(whl)

    def test_task_runs_in_pip_env(self, ray_start_shared, wheel):
        @ray_tpu.remote(runtime_env={"pip": [wheel]})
        def use_pkg():
            import tinypkg
            return tinypkg.greet()

        assert ray_tpu.get(use_pkg.remote(), timeout=180) == \
            "hi-from-tinypkg"

        # Outside the env the package must NOT be importable.
        @ray_tpu.remote
        def no_pkg():
            try:
                import tinypkg  # noqa: F401
                return "importable"
            except ImportError:
                return "absent"

        assert ray_tpu.get(no_pkg.remote(), timeout=60) == "absent"

    def test_env_cached_across_tasks(self, ray_start_shared, wheel):
        import os

        from ray_tpu._private.runtime_env import ensure_pip_env
        py1 = ensure_pip_env([wheel])
        ready = os.path.join(os.path.dirname(os.path.dirname(py1)),
                             ".ready")
        mtime1 = os.path.getmtime(ready)
        py2 = ensure_pip_env([wheel])
        # Cached: the second call must NOT rebuild the venv.
        assert py1 == py2 and os.path.getmtime(ready) == mtime1

    def test_bad_requirement_fails_task_not_livelock(
            self, ray_start_shared):
        from ray_tpu._private.runtime_env import RuntimeEnvSetupError

        @ray_tpu.remote(runtime_env={"pip": ["definitely-not-a-pkg-xyz"]},
                        max_retries=0)
        def f():
            return 1

        with pytest.raises(RuntimeEnvSetupError):
            ray_tpu.get(f.remote(), timeout=180)


class TestUvCondaRuntimeEnv:
    """uv runtime envs (reference: _private/runtime_env/uv.py) — built
    with the real `uv` tool, offline, cached by requirements hash — and
    the conda gating path (reference: runtime_env/conda.py)."""

    wheel = TestPipRuntimeEnv.wheel  # same on-the-spot wheel fixture

    def test_task_runs_in_uv_env_offline(self, ray_start_shared, wheel):
        """VERDICT r2 #9 done-when: a task runs in a uv-created env
        offline."""
        import shutil
        if shutil.which("uv") is None:
            pytest.skip("uv not installed")

        @ray_tpu.remote(runtime_env={"uv": [wheel]})
        def use_pkg():
            import sys

            import tinypkg
            return tinypkg.greet(), sys.executable

        greeting, worker_py = ray_tpu.get(use_pkg.remote(), timeout=180)
        assert greeting == "hi-from-tinypkg"
        assert "ray_tpu_envs" in worker_py  # ran the env's interpreter

    def test_uv_and_pip_envs_are_distinct(self, ray_start_shared, wheel):
        import shutil
        if shutil.which("uv") is None:
            pytest.skip("uv not installed")
        from ray_tpu._private.runtime_env import ensure_pip_env
        py_uv = ensure_pip_env([wheel], tool="uv")
        py_pip = ensure_pip_env([wheel], tool="pip")
        assert py_uv != py_pip  # different resolvers, different caches
        # Cached on re-request.
        assert ensure_pip_env([wheel], tool="uv") == py_uv

    def test_conda_without_tool_raises_clear_error(self,
                                                   ray_start_shared):
        import shutil
        if shutil.which("conda") or shutil.which("mamba") \
                or shutil.which("micromamba"):
            pytest.skip("conda present; gating path not reachable")
        from ray_tpu._private import runtime_env as re_mod
        with pytest.raises(ValueError, match="conda/mamba"):
            re_mod.validate({"conda": {"dependencies": ["python=3.12"]}})

    def test_container_still_gated(self, ray_start_shared):
        from ray_tpu._private import runtime_env as re_mod
        with pytest.raises(ValueError, match="container"):
            re_mod.validate({"container": {"image": "x"}})
