"""Direct worker<->worker call plane (_private/direct.py).

Covers the tentpole's failure-semantics contract: callee death with
open channels (kill() and raw SIGKILL) drains in-flight direct calls
into typed errors with correct retry `attempt` accounting; seeded
`direct.connect` drops fall back to the head path deterministically;
and the falsy `direct_calls_enabled` flag routes everything through the
head path with ZERO additional work (counter-based perf_smoke guard).

The whole module runs under the runtime lock-order tracker
(RAY_TPU_LOCKDEP=1 via the conftest guard) — any potential ABBA cycle
recorded by the new channel/accounting locks fails the test.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private.config import ray_config


@pytest.fixture(autouse=True)
def _force_direct_plane():
    """These tests exercise the direct plane itself: force it on even
    when the surrounding suite runs with RAY_TPU_DIRECT_CALLS_ENABLED=0
    (the flag-off acceptance sweep). Clearing the env override is
    enough — the scheduler propagates the driver's live config value
    into worker environments. test_disabled_flag_zero_direct_work
    manages its own (stricter) override on top of this."""
    prev_env = os.environ.pop("RAY_TPU_DIRECT_CALLS_ENABLED", None)
    prev_cfg = ray_config.direct_calls_enabled
    ray_config.set("direct_calls_enabled", True)
    yield
    ray_config.set("direct_calls_enabled", prev_cfg)
    if prev_env is not None:
        os.environ["RAY_TPU_DIRECT_CALLS_ENABLED"] = prev_env


@pytest.fixture
def fresh():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Echo:
    def echo(self, x):
        return x

    def pid(self):
        return os.getpid()

    def pair(self, x):
        return x, x + 1

    def boom(self):
        raise ValueError("boom from callee")

    def sleepy(self, s=1.0):
        time.sleep(s)
        return "ok"

    def big(self, n):
        return b"x" * n


@ray_tpu.remote
class Via:
    """Worker-side caller: every method drives the callee over the
    direct channel (the caller is a worker, the callee is alive)."""

    def __init__(self, callee):
        self.callee = callee

    def call(self, x):
        return ray_tpu.get(self.callee.echo.remote(x))

    def call_pair(self, x):
        a, b = self.callee.pair.options(num_returns=2).remote(x)
        return ray_tpu.get([a, b])

    def call_ref(self, ref):
        return ray_tpu.get(self.callee.echo.remote(ref))

    def call_boom(self):
        return ray_tpu.get(self.callee.boom.remote())

    def call_big(self, n):
        return len(ray_tpu.get(self.callee.big.remote(n)))

    def drive(self, n):
        return ray_tpu.get(
            [self.callee.echo.remote(i) for i in range(n)])

    def slow_roundtrip(self, s=1.0, retries=0):
        return ray_tpu.get(self.callee.sleepy.options(
            max_task_retries=retries).remote(s))

    def channel_state(self):
        """(direct ops so far, #live channels, #fallback pins)."""
        from ray_tpu._private import direct, state
        plane = state._worker.direct
        live = fall = 0
        for v in plane._chans.values():
            if isinstance(v, direct._Fallback):
                fall += 1
            else:
                live += 1
        return direct.direct_ops(), live, fall

    def fault_log(self):
        from ray_tpu._private import fault
        return fault.injection_log()


def test_direct_calls_basic(fresh):
    callee = Echo.remote()
    via = Via.remote(callee)
    assert ray_tpu.get(via.call.remote(41)) == 41
    assert ray_tpu.get(via.call_pair.remote(1)) == [1, 2]
    # Ref args resolve through the caller-supplied location / head.
    ref = ray_tpu.put({"k": 7})
    assert ray_tpu.get(via.call_ref.remote(ref)) == {"k": 7}
    # Errors surface typed at the caller's get.
    with pytest.raises(Exception, match="boom from callee"):
        ray_tpu.get(via.call_boom.remote())
    # The channel survives an error and keeps serving.
    assert ray_tpu.get(via.call.remote("again")) == "again"
    # Shm-backed (above inline threshold) results flow through the
    # shared store with head accounting for the segment.
    assert ray_tpu.get(via.call_big.remote(512 * 1024)) == 512 * 1024
    ops, live, fall = ray_tpu.get(via.channel_state.remote())
    assert live == 1 and fall == 0
    assert ops > 0  # the calls above actually took the direct path


def test_direct_calls_preserve_order(fresh):
    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.items = []

        def add(self, i):
            self.items.append(i)

        def items_(self):
            return list(self.items)

    @ray_tpu.remote
    class Driver:
        def __init__(self, log):
            self.log = log

        def run(self, n):
            refs = [self.log.add.remote(i) for i in range(n)]
            ray_tpu.get(refs)
            return ray_tpu.get(self.log.items_.remote())

    log = Log.remote()
    drv = Driver.remote(log)
    assert ray_tpu.get(drv.run.remote(200)) == list(range(200))


def test_kill_callee_with_open_channel(fresh):
    callee = Echo.remote()
    via = Via.remote(callee)
    assert ray_tpu.get(via.call.remote(1)) == 1  # channel established
    ray_tpu.kill(callee)
    with pytest.raises(Exception, match="ActorDied|Actor"):
        ray_tpu.get(via.call.remote(2), timeout=30)
    # The caller worker survives and serves fresh channels.
    callee2 = Echo.remote()
    via2 = Via.remote(callee2)
    assert ray_tpu.get(via2.call.remote(3)) == 3


def test_sigkill_callee_inflight_drains_typed(fresh):
    callee = Echo.remote()
    via = Via.remote(callee)
    pid = ray_tpu.get(via.call.remote(0)) or ray_tpu.get(
        callee.pid.remote())
    fut = via.slow_roundtrip.remote(2.0)
    time.sleep(0.5)  # the direct call is in flight on the callee
    os.kill(pid, signal.SIGKILL)
    # No retries budgeted: the reconcile must surface ActorDiedError
    # through the caller's local wait, not hang it.
    with pytest.raises(Exception, match="ActorDied|died"):
        ray_tpu.get(fut, timeout=30)


def test_sigkill_restart_retries_with_attempt_accounting():
    ray_tpu.init(num_cpus=4)
    try:
        callee = Echo.options(max_restarts=1).remote()
        via = Via.remote(callee)
        pid = ray_tpu.get(callee.pid.remote())
        assert ray_tpu.get(via.call.remote(1)) == 1
        fut = via.slow_roundtrip.remote(2.0, 1)  # max_task_retries=1
        time.sleep(0.5)
        os.kill(pid, signal.SIGKILL)
        # The reconcile requeues the in-flight spec onto the restarted
        # actor; the caller's local wait demotes to the head path and
        # resolves when the retry lands.
        assert ray_tpu.get(fut, timeout=60) == "ok"
        from ray_tpu._private import state
        node = state.get_node()
        attempts = [ev.get("attempt") for ev in
                    node.gcs.telemetry.events()
                    if "sleepy" in (ev.get("name") or "")]
        assert any((a or 0) >= 2 for a in attempts), attempts
    finally:
        ray_tpu.shutdown()


def _run_with_connect_drops(seed):
    ray_tpu.init(num_cpus=4, fault_config={
        "seed": seed,
        "rules": [{"site": "direct.connect", "action": "drop",
                   "prob": 1.0}]})
    try:
        callee = Echo.remote()
        via = Via.remote(callee)
        # Every channel dial is dropped: calls MUST fall back to the
        # head-routed path and still succeed.
        assert ray_tpu.get(via.drive.remote(20)) == list(range(20))
        _ops, live, fall = ray_tpu.get(via.channel_state.remote())
        log = ray_tpu.get(via.fault_log.remote())
        assert live == 0 and fall == 1
        return log
    finally:
        ray_tpu.shutdown()


def test_fault_direct_connect_drop_falls_back_deterministically():
    log1 = _run_with_connect_drops(11)
    log2 = _run_with_connect_drops(11)
    assert log1, "direct.connect never fired under the fault plane"
    assert all(site == "direct.connect" and action == "drop"
               for site, _seq, action in log1)
    # Same seed, same per-site firing counts => identical schedules.
    assert log1 == log2


@pytest.mark.perf_smoke
def test_disabled_flag_zero_direct_work():
    """With direct_calls_enabled=false the submit/complete paths do ZERO
    direct-plane work (counter-based, wall-clock-free — the telemetry/
    lockdep guard style) and everything rides the head path."""
    prev_env = os.environ.get("RAY_TPU_DIRECT_CALLS_ENABLED")
    ray_config.set("direct_calls_enabled", False)
    try:
        ray_tpu.init(num_cpus=4)
        try:
            callee = Echo.remote()
            via = Via.remote(callee)
            assert ray_tpu.get(via.drive.remote(50)) == list(range(50))
            assert ray_tpu.get(via.call_pair.remote(5)) == [5, 6]
            with pytest.raises(Exception, match="boom"):
                ray_tpu.get(via.call_boom.remote())
            ops, live, fall = ray_tpu.get(via.channel_state.remote())
            assert ops == 0, f"direct plane did {ops} ops while disabled"
            assert live == 0 and fall == 0
            # Head side took the classic path end to end.
            from ray_tpu._private import direct, state
            node = state.get_node()
            assert node._direct_on is False
            assert direct.direct_ops() == 0  # driver-side plane untouched
            # kill() semantics are intact on the fallback path.
            ray_tpu.kill(callee)
            with pytest.raises(Exception, match="ActorDied|Actor"):
                ray_tpu.get(via.call.remote(1), timeout=30)
        finally:
            ray_tpu.shutdown()
    finally:
        ray_config.set("direct_calls_enabled", True)
        if prev_env is None:
            os.environ.pop("RAY_TPU_DIRECT_CALLS_ENABLED", None)
        else:
            os.environ["RAY_TPU_DIRECT_CALLS_ENABLED"] = prev_env


def test_dial_while_serving_channel_open(fresh):
    """n:n topology (acyclic): worker A DIALS out to B while another
    worker C dials A, so A's recv loop must serve the inbound
    CHANNEL_OPEN while A's own outbound _establish is blocked in a
    broker request — listener creation must never contend on the
    establishment lock, or the REPLY that completes the dial can
    never be processed and A's whole control plane wedges."""

    @ray_tpu.remote
    class Peer:
        def ping(self, x):
            return x

        def relay(self, other, x):
            return ray_tpu.get(other.ping.remote(x))

    a = Peer.remote()
    b = Peer.remote()
    c = Peer.remote()
    # Warm nothing: the FIRST a.relay dial (a->b) and the first
    # c.relay dial (c->a, landing CHANNEL_OPEN on a's recv loop)
    # race by construction. The call graph is acyclic (c->a->b), so
    # any hang is a plane bug, not actor-reentrancy blocking.
    refs = [a.relay.remote(b, i) for i in range(10)] \
        + [c.relay.remote(a, 100 + i) for i in range(10)]
    assert ray_tpu.get(refs, timeout=60) == \
        list(range(10)) + [100 + i for i in range(10)]


def test_fault_direct_call_drop_falls_back():
    """Seeded `direct.call` drops (the send raises AFTER the call is
    registered in-flight) must unwind the registration and fall back
    to the head path — no duplicate execution, no absorbed-ref leak."""
    ray_tpu.init(num_cpus=4, fault_config={
        "seed": 23,
        "rules": [{"site": "direct.call", "action": "drop",
                   "prob": 1.0}]})
    try:
        @ray_tpu.remote
        class Count:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        callee = Count.remote()

        @ray_tpu.remote
        class Drv:
            def __init__(self, c):
                self.c = c

            def run(self, k):
                return [ray_tpu.get(self.c.bump.remote())
                        for _ in range(k)]

        d = Drv.remote(callee)
        # Exactly-once execution proves the dropped sends rolled back
        # (a double-owned spec would bump twice or hang the get).
        assert ray_tpu.get(d.run.remote(10), timeout=60) == \
            list(range(1, 11))
    finally:
        ray_tpu.shutdown()


def test_nested_submission_result_forwarding(fresh):
    """Nested plain-task results resolve through the head->submitter
    push (RESULT_FWD) with no pull round trip; errors forward too."""

    @ray_tpu.remote
    def ok(i):
        return i * 2

    @ray_tpu.remote
    def bad():
        raise RuntimeError("nested boom")

    @ray_tpu.remote
    class Sub:
        def batch(self, n):
            return ray_tpu.get([ok.remote(i) for i in range(n)])

        def fail(self):
            try:
                ray_tpu.get(bad.remote(), timeout=30)
                return "no error"
            except Exception as e:
                return f"caught: {e}"

    s = Sub.remote()
    assert ray_tpu.get(s.batch.remote(100)) == [i * 2 for i in range(100)]
    assert "nested boom" in ray_tpu.get(s.fail.remote())


def test_escaped_inflight_ref_resolves_on_idle_caller(fresh):
    """A direct-call ref that ESCAPES the caller (returned inside its
    own task result) while the call is still in flight hands the head
    a waiter; the caller then goes idle. The retirement must flush the
    completion entry — and the flush must not elide it just because
    the caller's local residual netted zero — or the driver's get on
    the escaped ref hangs forever (regression: it did)."""

    @ray_tpu.remote
    class Maker:
        def __init__(self, callee):
            self.callee = callee

        def spawn(self):
            ray_tpu.get(self.callee.echo.remote(0))  # warm the channel
            return self.callee.sleepy.remote(1.0)  # escapes in flight

        def spawn_done(self):
            r = self.callee.echo.remote(7)
            ray_tpu.get(r)  # retired (parked) before it escapes
            return r

    callee = Echo.remote()
    mk = Maker.remote(callee)
    inner = ray_tpu.get(mk.spawn.remote())
    assert ray_tpu.get(inner, timeout=30) == "ok"
    inner2 = ray_tpu.get(mk.spawn_done.remote())
    assert ray_tpu.get(inner2, timeout=30) == 7


def test_retry_exceptions_calls_stay_head_routed(fresh):
    """retry_exceptions is a HEAD decision (TASK_DONE's resubmit
    branch): on the channel the error blob would retire terminally at
    the caller with zero retries, so such calls must not ship direct —
    flag-on and flag-off behavior stays identical."""

    @ray_tpu.remote
    class Flaky:
        def __init__(self):
            self.n = 0

        def once(self):
            self.n += 1
            if self.n == 1:
                raise ValueError("transient boom")
            return self.n

    @ray_tpu.remote
    class Drv:
        def __init__(self, c):
            self.c = c

        def run(self):
            return ray_tpu.get(self.c.once.options(
                retry_exceptions=True, max_task_retries=2).remote(),
                timeout=30)

    f = Flaky.remote()
    d = Drv.remote(f)
    assert ray_tpu.get(d.run.remote(), timeout=60) == 2


def test_pending_callee_does_not_pin_fallback(fresh):
    """A first call racing the callee's construction gets a TRANSIENT
    broker refusal: it rides the head path, but the pair must not be
    pinned to _FALLBACK — once the actor is up, the next call
    establishes the channel. (Regression: under load the warm-up race
    permanently cost the pair its direct plane.)"""

    @ray_tpu.remote
    class SlowEcho:
        def __init__(self):
            time.sleep(1.5)

        def echo(self, x):
            return x

    callee = SlowEcho.remote()
    via = Via.remote(callee)
    # Submitted while the callee is still in __init__: the broker
    # replies transient, the call completes head-routed.
    assert ray_tpu.get(via.call.remote(1)) == 1
    assert ray_tpu.get(via.call.remote(2)) == 2
    ops, live, fall = ray_tpu.get(via.channel_state.remote())
    assert fall == 0, "pending callee wrongly pinned the fallback path"
    assert live == 1


def test_config_set_overrides_exported_env_in_workers():
    """A programmatic ray_config.set on the driver must reach worker
    environments even when the operator's shell exported the opposite
    value — a worker marking results forward-pending while the head
    never forwards would stall every nested get 5s (the resync
    deadline) before degrading to a pull."""
    prev_env = os.environ.get("RAY_TPU_DIRECT_RESULT_FORWARDING")
    os.environ["RAY_TPU_DIRECT_RESULT_FORWARDING"] = "1"
    prev_cfg = ray_config.direct_result_forwarding
    ray_config.set("direct_result_forwarding", False)
    try:
        ray_tpu.init(num_cpus=2)
        try:
            @ray_tpu.remote
            def worker_env(k):
                return os.environ.get(k)

            assert ray_tpu.get(worker_env.remote(
                "RAY_TPU_DIRECT_RESULT_FORWARDING")) == "0"
        finally:
            ray_tpu.shutdown()
    finally:
        ray_config.set("direct_result_forwarding", prev_cfg)
        if prev_env is None:
            os.environ.pop("RAY_TPU_DIRECT_RESULT_FORWARDING", None)
        else:
            os.environ["RAY_TPU_DIRECT_RESULT_FORWARDING"] = prev_env


def test_direct_shm_result_registers_lineage(fresh):
    """SHM-backed direct-call results carry their producing spec to the
    head inside the DIRECT_DONE entry, so the object directory holds
    lineage exactly like the head-routed TASK_DONE path — losing the
    backing node must leave the object reconstructable, not dead."""

    @ray_tpu.remote
    class Maker:
        def __init__(self, callee):
            self.callee = callee

        def make(self, n):
            ref = self.callee.big.remote(n)
            ray_tpu.get(ref)  # retire caller-side (entry parks)
            return [ref]

    callee = Echo.remote()
    mk = Maker.remote(callee)
    (ref,) = ray_tpu.get(mk.make.remote(512 * 1024))
    from ray_tpu._private import state
    node = state.get_node()
    entry = node.gcs.objects.entry(ref.id)
    assert entry is not None and entry.event.is_set()
    assert entry.lineage is not None, \
        "direct SHM result registered without lineage"
    assert entry.lineage.method_name == "big"


def test_gen_cancel_stops_producer_on_release(fresh):
    """Dropping a channel-stream generator mid-iteration ships a
    GEN_CANCEL frame over the channel: the callee's producing
    generator is interrupted instead of running (and shipping items
    into the abandoned stream) to completion — closing the PERF.md
    deviation where only the head-routed path cancelled. The module's
    refdebug guard additionally holds the cancel path to a clean
    conservation replay (in-flight items balance at terminal)."""

    @ray_tpu.remote
    class Producer:
        def __init__(self):
            self.produced = 0

        def stream(self, n):
            for i in range(n):
                self.produced += 1
                time.sleep(0.05)
                yield i

        def count(self):
            return self.produced

    @ray_tpu.remote
    class Consumer:
        def __init__(self, producer):
            self.producer = producer

        def take_two(self):
            gen = self.producer.stream.options(
                num_returns="streaming").remote(200)
            it = iter(gen)
            out = [ray_tpu.get(next(it)), ray_tpu.get(next(it))]
            del it, gen  # mid-iteration drop -> gen_release -> cancel
            return out

    producer = Producer.remote()
    consumer = Consumer.remote(producer)
    assert ray_tpu.get(consumer.take_two.remote(), timeout=60) == [0, 1]
    # The producer must stop well short of n: poll until its yield
    # count stabilizes (the cancel lands asynchronously).
    last, deadline = -1, time.monotonic() + 30
    while time.monotonic() < deadline:
        cur = ray_tpu.get(producer.count.remote(), timeout=30)
        if cur == last:
            break
        last = cur
        time.sleep(0.3)
    assert last < 150, \
        f"producer yielded {last}/200 items after the stream was dropped"
