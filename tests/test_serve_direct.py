"""Serve data plane on the direct call plane (docs/PERF.md serve
section).

Tier-1: proxy requests ride brokered replica channels (head hears
nothing per request), big bodies move through the same-node arena with
conserved frees, the flag-off path does ZERO serve-direct work
(counter-based perf_smoke guard), queue-full admission sheds 503 at
the edge, replica SIGKILL mid-request surfaces a typed 503 instead of
a hang, and the gRPC proxy rides the same dispatch helper. Chaos tier
(slow): HTTP drain-mid-load with zero failed requests.

Runs under both the lockdep tracker and the refdebug conservation
ledger (conftest registries): the serve channels add a writer + recv
thread per replica and arena-staged bodies add put/free pairs — both
must come out clean.
"""
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu as ray
from ray_tpu import serve
from ray_tpu._private import direct
from ray_tpu._private import state as _state
from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ObjectID
from ray_tpu.serve._private.direct_client import serve_direct_ops

# An operator forcing the plane off for a whole run (the flag-off
# byte-identical sweep in the PR acceptance) should see these tests
# skip, not fail asserting direct work that can't happen. The
# flag-off zero-work guard below flips the config in-process and is
# exempt.
requires_direct_plane = pytest.mark.skipif(
    os.environ.get("RAY_TPU_SERVE_DIRECT_ENABLED", "").lower()
    in ("0", "false", "no", "off"),
    reason="serve direct plane disabled via RAY_TPU_SERVE_DIRECT_ENABLED",
)


@pytest.fixture
def clean_serve():
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray.shutdown()


def _post(addr: str, payload, timeout: float = 30.0):
    """POST a JSON body; returns (status, decoded_body) and never
    raises on HTTP error statuses (the shed/unavailable tests assert
    on them)."""
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        addr + "/", data=data, headers={"Content-Type": "application/json"})
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        raw = resp.read()
        status = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read()
        status = e.code
    try:
        return status, json.loads(raw)
    except ValueError:
        return status, raw.decode(errors="replace")


def _drive_until_direct(addr, payload, expect, deadline_s=30.0):
    """Requests succeed from the first one (head path while the channel
    establishes); returns once at least one rode the direct plane."""
    before = serve_direct_ops()
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        status, out = _post(addr, payload)
        assert status == 200 and out == expect, (status, out)
        if serve_direct_ops() > before:
            return
        time.sleep(0.05)
    pytest.fail("no request rode the direct serve plane within "
                f"{deadline_s}s (ops stuck at {before})")


@requires_direct_plane
def test_direct_round_trip(clean_serve):
    """Steady-state proxy requests ride SERVE_REQ/SERVE_RESP channel
    frames; correctness is byte-identical to the head path."""
    ray.init(num_cpus=4)

    @serve.deployment(num_replicas=2)
    def echo(request):
        return {"x": request["body"]["x"] * 2}

    serve.run(echo.bind())
    addr = serve.proxy_address()
    _drive_until_direct(addr, {"x": 21}, {"x": 42})
    before = serve_direct_ops()
    for i in range(30):
        status, out = _post(addr, {"x": i})
        assert status == 200 and out == {"x": i * 2}, (status, out, i)
    # Channel established: EVERY one of those rode the direct plane
    # (call + response per request at minimum).
    assert serve_direct_ops() - before >= 60


@pytest.mark.perf_smoke
def test_disabled_flag_zero_direct_work(clean_serve):
    """serve_direct_enabled=false does ZERO serve-direct work — not
    "cheap", zero, proven by the op counter (same discipline as the
    direct-call plane's guard in scripts/ci_fast.sh)."""
    ray.init(num_cpus=2)
    entry_value = ray_config.serve_direct_enabled
    ray_config.set("serve_direct_enabled", False)
    try:
        @serve.deployment(num_replicas=1)
        def echo(request):
            return {"x": request["body"]["x"] + 1}

        serve.run(echo.bind())
        addr = serve.proxy_address()
        before = serve_direct_ops()
        for i in range(20):
            status, out = _post(addr, {"x": i})
            assert status == 200 and out == {"x": i + 1}, (status, out)
        assert serve_direct_ops() == before
    finally:
        # Restore what the RUN had (env overrides included), not the
        # compiled default — a flag-off sweep must stay flag-off.
        ray_config.set("serve_direct_enabled", entry_value)


@requires_direct_plane
def test_body_codec_stages_large_same_node_only(clean_serve):
    """serve_encode_body inlines small and cross-node bodies, stages
    large same-node ones in the node store; the consumer (a SECOND
    client instance over the same node dir — the real two-process
    shape) maps them in place, and the producer-side free on the ack
    leaves the slot released."""
    ray.init(num_cpus=1)
    store = _state.get_node().store
    arena_path = getattr(store, "_path", None)
    if isinstance(arena_path, str):
        consumer = type(store)(os.path.dirname(arena_path))
    else:
        consumer = type(store)(store._dir)
    big = b"x" * (2 * int(ray_config.serve_direct_body_threshold))
    enc = direct.serve_encode_body(store, big, True)
    assert enc[0] == "o", enc[:1]
    used_before_free = store.used_bytes
    assert used_before_free > 0
    value, free_ob = direct.serve_decode_body(consumer, enc)
    assert value == big
    assert free_ob == enc[1]
    store.free(ObjectID(free_ob))  # what the consumer's FREE ack runs
    assert store.used_bytes < used_before_free
    assert direct.serve_encode_body(store, b"small", True)[0] == "i"
    # Cross-node bodies never stage: the staging store is per-node.
    assert direct.serve_encode_body(store, big, False)[0] == "i"


@requires_direct_plane
def test_big_body_zero_copy_round_trip(clean_serve):
    """Request AND response bodies above the threshold ride the arena
    (SERVE_BODY_FREE acks both directions) and round-trip intact."""
    ray.init(num_cpus=2)
    ray_config.set("serve_direct_body_threshold", 4096)
    try:
        @serve.deployment(num_replicas=1)
        def blob(request):
            body = request["body"]
            return {"echo": body["data"], "resp_pad": "y" * 100_000}

        serve.run(blob.bind())
        addr = serve.proxy_address()
        _drive_until_direct(addr, {"data": "w"},
                            {"echo": "w", "resp_pad": "y" * 100_000})
        for i in range(5):
            payload = {"data": f"{i}:" + "z" * 50_000}
            status, out = _post(addr, payload)
            assert status == 200, (status, out)
            assert out["echo"] == payload["data"]
            assert out["resp_pad"] == "y" * 100_000
    finally:
        ray_config.set(
            "serve_direct_body_threshold",
            ray_config._DEFAULTS["serve_direct_body_threshold"])


@requires_direct_plane
def test_replica_sigkill_mid_request_typed_503(clean_serve):
    """SIGKILL of the replica with a request in flight on its channel:
    the EOF fans a typed error into the waiter and the proxy answers
    503 — never a hang — then the restarted replica serves again."""
    ray.init(num_cpus=4)

    @serve.deployment(num_replicas=1)
    def victim(request):
        body = request["body"]
        if body.get("op") == "pid":
            return {"pid": os.getpid()}
        time.sleep(float(body.get("sleep", 0)))
        return {"ok": True}

    serve.run(victim.bind())
    addr = serve.proxy_address()
    # Establish the channel first so the slow request below
    # deterministically rides it.
    before = serve_direct_ops()
    deadline = time.monotonic() + 30
    pid = None
    while time.monotonic() < deadline:
        status, out = _post(addr, {"op": "pid"})
        assert status == 200, (status, out)
        pid = out["pid"]
        if serve_direct_ops() > before:
            break
        time.sleep(0.05)
    assert serve_direct_ops() > before, "channel never established"

    result = {}

    def slow():
        result["resp"] = _post(addr, {"sleep": 30}, timeout=60)

    t = threading.Thread(target=slow, daemon=True)
    t.start()
    time.sleep(1.0)  # request is in flight on the channel
    os.kill(pid, signal.SIGKILL)
    t.join(timeout=30)
    assert not t.is_alive(), "in-flight request HUNG across replica death"
    status, out = result["resp"]
    assert status == 503, (status, out)
    assert "replica" in json.dumps(out).lower(), out

    # The controller restarts the replica; traffic recovers.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        status, out = _post(addr, {"op": "pid"})
        if status == 200 and out["pid"] != pid:
            break
        time.sleep(0.25)
    else:
        pytest.fail("replica never came back after SIGKILL")


@requires_direct_plane
def test_queue_full_sheds_503(clean_serve):
    """When every replica's proxy-tracked queue is at
    serve_max_queue_per_replica, the proxy sheds with 503 at the edge
    instead of stacking requests behind a wedged pool."""
    ray.init(num_cpus=2)
    ray_config.set("serve_max_queue_per_replica", 2)
    try:
        @serve.deployment(num_replicas=1, max_ongoing_requests=16)
        def slowpoke(request):
            time.sleep(float(request["body"].get("sleep", 0)))
            return {"ok": True}

        serve.run(slowpoke.bind())
        addr = serve.proxy_address()
        _drive_until_direct(addr, {"sleep": 0}, {"ok": True})

        results = []
        lock = threading.Lock()

        def fire():
            r = _post(addr, {"sleep": 2.0}, timeout=60)
            with lock:
                results.append(r)

        threads = [threading.Thread(target=fire) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        statuses = [s for s, _ in results]
        assert statuses.count(200) >= 1, results
        shed = [(s, b) for s, b in results if s == 503]
        assert shed, f"no request was shed: {statuses}"
        assert "in flight" in json.dumps(shed[0][1]), shed[0]
    finally:
        ray_config.set("serve_max_queue_per_replica",
                       ray_config._DEFAULTS["serve_max_queue_per_replica"])


@requires_direct_plane
def test_grpc_rides_same_dispatch(clean_serve):
    """The gRPC proxy goes through the SAME dispatch helper: its unary
    calls ride the direct channels too (one data plane, two fronts)."""
    pytest.importorskip("grpc")
    ray.init(num_cpus=2)

    @serve.deployment(num_replicas=1)
    class Adder:
        def __call__(self, a, b):
            return a + b

    serve.run(Adder.bind(), name="gapp")
    proxy = serve.start_grpc()
    from ray_tpu.serve._private.grpc_proxy import GrpcServeClient
    client = GrpcServeClient(f"127.0.0.1:{proxy.port}")
    try:
        before = serve_direct_ops()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            assert client.call("gapp", 2, 3) == 5
            if serve_direct_ops() > before:
                break
            time.sleep(0.05)
        assert serve_direct_ops() > before, \
            "gRPC unary never rode the direct plane"
    finally:
        client.close()


@pytest.mark.slow
@pytest.mark.chaos
@requires_direct_plane
def test_http_drain_mid_load_zero_failed(clean_serve):
    """Drain a node hosting replicas while HTTP requests flow through
    the proxy on direct channels: every request succeeds through the
    drain AND after the hard node removal (the zero-loss scale-down
    contract of docs/DRAIN.md, on the serve data plane)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.state import drain_node, drain_status, list_actors

    ray.init(num_cpus=1)
    cluster = Cluster()
    a = cluster.add_node(num_cpus=2, daemon=True)
    b = cluster.add_node(num_cpus=2, daemon=True)
    try:
        @serve.deployment(num_replicas=3, max_ongoing_requests=8,
                          ray_actor_options={"num_cpus": 1})
        def app(request):
            time.sleep(0.01)
            return {"x": request["body"]["x"] * 2}

        serve.run(app.bind(), name="drain_http")
        addr = serve.proxy_address()
        for i in range(10):
            status, out = _post(addr, {"x": i})
            assert status == 200 and out == {"x": i * 2}, (status, out)

        replica_nodes = {r["node_id"] for r in list_actors()
                         if "SERVE_REPLICA" in (r["name"] or "")
                         and r["state"] not in ("DEAD",)}
        victim = a if a.node_id in replica_nodes else b

        st = drain_node(victim.node_id, wait=False)
        assert st["state"] == "DRAINING", st
        served = 0
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            status, out = _post(addr, {"x": served})
            assert status == 200 and out == {"x": served * 2}, \
                (status, out, served)
            served += 1
            if drain_status(victim.node_id)["state"] != "DRAINING":
                break
        assert drain_status(victim.node_id)["state"] == "DRAINED"
        assert served > 0

        cluster.remove_node(victim, allow_graceful=False)
        for i in range(10):
            status, out = _post(addr, {"x": i})
            assert status == 200 and out == {"x": i * 2}, (status, out)
    finally:
        cluster.shutdown()
