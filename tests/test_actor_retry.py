"""Actor task retries: exception retries, death retries, and the data
actor pool surviving worker failures mid-stream.

Reference strategy: python/ray/tests/test_actor_failures.py
(max_task_retries / retry_exceptions on actor methods; actor restart
replays in-flight tasks) and data/tests for ActorPoolMapOperator worker
replacement (actor_pool_map_operator.py:34,446).
"""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu as ray


@pytest.fixture(scope="module", autouse=True)
def _init():
    ray.init(num_cpus=4, ignore_reinit_error=True)
    yield


def _marker():
    fd, path = tempfile.mkstemp(prefix="ray_tpu_retry_")
    os.close(fd)
    os.unlink(path)
    return path


def test_actor_method_retry_exceptions():
    @ray.remote
    class Flaky:
        def __init__(self):
            self.calls = 0

        def hello(self):
            self.calls += 1
            if self.calls < 3:
                raise RuntimeError(f"transient {self.calls}")
            return self.calls

    a = Flaky.remote()
    got = ray.get(a.hello.options(retry_exceptions=True,
                                  max_task_retries=3).remote())
    assert got == 3


def test_actor_method_no_retry_by_default():
    @ray.remote
    class Flaky:
        def boom(self):
            raise RuntimeError("once")

    a = Flaky.remote()
    with pytest.raises(Exception, match="once"):
        ray.get(a.boom.remote())


def test_actor_death_retries_inflight_task():
    """A worker that dies MID-TASK: the actor restarts (max_restarts)
    and the in-flight call re-runs on the fresh instance
    (max_task_retries) instead of raising ActorDiedError."""
    marker = _marker()

    @ray.remote(max_restarts=1, max_task_retries=2)
    class DieOnce:
        def work(self, marker):
            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)  # simulated crash mid-task
            return "survived"

    a = DieOnce.remote()
    try:
        assert ray.get(a.work.remote(marker), timeout=60) == "survived"
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_actor_death_without_retry_budget_fails():
    @ray.remote(max_restarts=1)  # restarts, but tasks have no budget
    class Dies:
        def work(self):
            os._exit(1)

    a = Dies.remote()
    with pytest.raises(ray.exceptions.ActorDiedError):
        ray.get(a.work.remote(), timeout=60)


def test_map_batches_actor_pool_survives_worker_death():
    """VERDICT r2 #5 done-when: an actor-pool map_batches pipeline
    completes even when one pool actor dies mid-run."""
    from ray_tpu import data as rdata

    marker = _marker()

    class KillerMapper:
        def __call__(self, batch):
            # First batch that sees no marker kills its worker.
            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)
            batch["x"] = batch["x"] * 2
            return batch

    try:
        ds = rdata.from_items([{"x": float(i)} for i in range(64)])
        out = ds.map_batches(KillerMapper, batch_size=8,
                             concurrency=2).take_all()
        assert sorted(r["x"] for r in out) == [2.0 * i for i in range(64)]
    finally:
        if os.path.exists(marker):
            os.unlink(marker)


def test_map_batches_actor_pool_survives_transient_exception():
    """The BENCH_r02 regression class: a transient in-actor exception
    (remote-compile hiccup) retries instead of killing the pipeline."""
    from ray_tpu import data as rdata

    marker = _marker()

    class FlakyMapper:
        def __call__(self, batch):
            if not os.path.exists(marker):
                open(marker, "w").close()
                raise RuntimeError("transient backend error")
            batch["x"] = batch["x"] + 1
            return batch

    try:
        ds = rdata.from_items([{"x": float(i)} for i in range(32)])
        out = ds.map_batches(FlakyMapper, batch_size=8,
                             concurrency=2).take_all()
        assert sorted(r["x"] for r in out) == [float(i + 1)
                                               for i in range(32)]
    finally:
        if os.path.exists(marker):
            os.unlink(marker)
