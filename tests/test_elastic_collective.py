"""Elastic collective re-initialization (SURVEY hard-part #3).

Chaos contract from VERDICT r1 #8: kill one of 4 collective workers
mid-train; the job must resume at world=3 — a fresh worker-process gang
re-runs the jax.distributed rendezvous with new membership (dodging the
once-per-process topology freeze), restores from the latest checkpoint,
and device collectives work at the new world size — all without
restarting the driver.
"""

import tempfile
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_kill_one_of_four_collective_workers(tmp_path):
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    nodes = [cluster.add_node(num_cpus=1, resources={"slot": 1})
             for _ in range(4)]
    marker = str(tmp_path / "phase1_running")

    def loop(config):
        import os

        import numpy as np

        from ray_tpu.util import collective as col

        ctx = train.get_context()
        world = ctx.world_size
        # Fresh gang, fresh rendezvous: the group name carries the
        # per-gang experiment uid, so restarted gangs never see the old
        # coordinator key.
        g = col.init_collective_group(
            world, ctx.world_rank, "xla",
            f"elastic/{ctx.experiment_name}")
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.to_state()["step"])
        for step in range(start, 6):
            # Device collective proves the group is live at THIS world.
            total = g.allreduce(np.ones((1,), np.float32))
            assert int(total[0]) == world, (total, world)
            if ctx.world_rank == 0:
                c = Checkpoint.from_state(
                    {"step": np.int32(step + 1)}, tempfile.mkdtemp())
                train.report({"step": step + 1, "world": world,
                              "coll_sum": float(total[0])}, checkpoint=c)
                if step >= 1:
                    open(config["marker"], "w").close()
            else:
                train.report({"step": step + 1})
            time.sleep(0.4)

    def killer():
        import os
        deadline = time.monotonic() + 120
        while not os.path.exists(marker):
            if time.monotonic() > deadline:
                return
            time.sleep(0.1)
        cluster.remove_node(nodes[-1])  # kills that worker's process

    try:
        t = threading.Thread(target=killer, daemon=True)
        t.start()
        trainer = JaxTrainer(
            loop, train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(
                num_workers=4, min_workers=1, max_workers=4,
                resources_per_worker={"CPU": 1, "slot": 1}),
            run_config=RunConfig(
                name="elastic", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2)))
        result = trainer.fit()
        t.join(timeout=10)
        assert result.error is None, result.error
        sizes = trainer._controller.world_sizes
        # First gang was 4-wide; after losing a node the elastic policy
        # re-formed the collective at 3.
        assert sizes[0] == 4, sizes
        assert sizes[-1] == 3, sizes
        assert result.metrics["step"] == 6
        assert result.metrics["world"] == 3
        assert result.metrics["coll_sum"] == 3.0
        # Resumed from checkpoint, not from scratch: the state machine
        # went through RESTARTING exactly once.
        states = [s for s, _ in trainer._controller.state_log]
        assert states.count("RESTARTING") == 1, states
    finally:
        cluster.shutdown()


def test_sigkill_daemon_mid_training_resumes_and_loss_descends(tmp_path):
    """The COMPOSED elastic story (SURVEY §7 hard-part #3, VERDICT r4
    weak #8) in one test: a real data-parallel training loop (linear
    model, gradient allreduce through the collective group) runs under
    JaxTrainer.fit on a 4-node virtual cluster; a node daemon is
    SIGKILLed mid-run (no graceful shutdown); the gang re-forms at
    world=3, resumes from the LATEST checkpoint (not step 0), and the
    loss keeps descending after the restart."""
    import json
    import os
    import signal

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    # daemon=True: REAL per-host daemon subprocesses, so the kill below
    # is a genuine node death (process SIGKILL), not a bookkeeping
    # removal.
    nodes = [cluster.add_node(num_cpus=1, resources={"slot": 1},
                              daemon=True)
             for _ in range(4)]
    marker = str(tmp_path / "mid_train")
    log_path = str(tmp_path / "steps.jsonl")

    def loop(config):
        import numpy as np

        from ray_tpu.util import collective as col

        ctx = train.get_context()
        world, rank = ctx.world_size, ctx.world_rank
        g = col.init_collective_group(
            world, rank, "xla", f"chaos/{ctx.experiment_name}")
        rng = np.random.default_rng(0)
        X = rng.normal(size=(240, 8)).astype(np.float32)
        y = (X @ np.arange(8, dtype=np.float32)).astype(np.float32)
        per = len(X) // world
        Xs, ys = X[rank * per:(rank + 1) * per], \
            y[rank * per:(rank + 1) * per]
        step0, w = 0, np.zeros(8, np.float32)
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            st = ckpt.to_state()
            step0, w = int(st["step"]), np.asarray(st["w"])
        for step in range(step0, 14):
            err = Xs @ w - ys
            loss = float((err ** 2).mean())
            grad = (2.0 * Xs.T @ err / len(ys)).astype(np.float32)
            gsum = g.allreduce(grad)          # DP gradient allreduce
            w = w - 0.05 * gsum / world
            gloss = float(g.allreduce(
                np.array([loss], np.float32))[0]) / world
            if rank == 0:
                c = Checkpoint.from_state(
                    {"step": np.int32(step + 1), "w": w},
                    tempfile.mkdtemp())
                train.report({"step": step + 1, "loss": gloss,
                              "world": world}, checkpoint=c)
                with open(config["log"], "a") as f:
                    f.write(json.dumps({"step": step + 1, "loss": gloss,
                                        "world": world}) + "\n")
                if step + 1 == 4:
                    open(config["marker"], "w").close()
            else:
                train.report({"step": step + 1})
            time.sleep(0.2)

    def killer():
        deadline = time.monotonic() + 120
        while not os.path.exists(marker):
            if time.monotonic() > deadline:
                return
            time.sleep(0.1)
        # HARD kill: SIGKILL the daemon process — no drain, no
        # goodbye; the head must detect the dropped connection
        # (reference: RayletKiller chaos semantics).
        nodes[-1].proc.send_signal(signal.SIGKILL)

    try:
        t = threading.Thread(target=killer, daemon=True)
        t.start()
        trainer = JaxTrainer(
            loop,
            train_loop_config={"marker": marker, "log": log_path},
            scaling_config=ScalingConfig(
                num_workers=4, min_workers=1, max_workers=4,
                resources_per_worker={"CPU": 1, "slot": 1}),
            run_config=RunConfig(
                name="chaos", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2)))
        result = trainer.fit()
        t.join(timeout=10)
        assert result.error is None, result.error
        sizes = trainer._controller.world_sizes
        assert sizes[0] == 4 and sizes[-1] == 3, sizes
        assert result.metrics["step"] == 14

        rows = [json.loads(line) for line in open(log_path)]
        worlds = {r["step"]: r["world"] for r in rows}
        # Resumed FROM THE CHECKPOINT: the first step logged at world=3
        # continues past the last checkpointed step — never back at 1.
        w3_steps = sorted(s for s, w in worlds.items() if w == 3)
        assert w3_steps and w3_steps[0] >= 4, rows
        # Loss keeps DESCENDING across the restart: the final loss is
        # below the loss at the kill point and the first loss.
        by_step = {r["step"]: r["loss"] for r in rows}
        assert by_step[14] < by_step[4] < by_step[1], by_step
    finally:
        cluster.shutdown()
