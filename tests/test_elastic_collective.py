"""Elastic collective re-initialization (SURVEY hard-part #3).

Chaos contract from VERDICT r1 #8: kill one of 4 collective workers
mid-train; the job must resume at world=3 — a fresh worker-process gang
re-runs the jax.distributed rendezvous with new membership (dodging the
once-per-process topology freeze), restores from the latest checkpoint,
and device collectives work at the new world size — all without
restarting the driver.
"""

import tempfile
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_kill_one_of_four_collective_workers(tmp_path):
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    nodes = [cluster.add_node(num_cpus=1, resources={"slot": 1})
             for _ in range(4)]
    marker = str(tmp_path / "phase1_running")

    def loop(config):
        import os

        import numpy as np

        from ray_tpu.util import collective as col

        ctx = train.get_context()
        world = ctx.world_size
        # Fresh gang, fresh rendezvous: the group name carries the
        # per-gang experiment uid, so restarted gangs never see the old
        # coordinator key.
        g = col.init_collective_group(
            world, ctx.world_rank, "xla",
            f"elastic/{ctx.experiment_name}")
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.to_state()["step"])
        for step in range(start, 6):
            # Device collective proves the group is live at THIS world.
            total = g.allreduce(np.ones((1,), np.float32))
            assert int(total[0]) == world, (total, world)
            if ctx.world_rank == 0:
                c = Checkpoint.from_state(
                    {"step": np.int32(step + 1)}, tempfile.mkdtemp())
                train.report({"step": step + 1, "world": world,
                              "coll_sum": float(total[0])}, checkpoint=c)
                if step >= 1:
                    open(config["marker"], "w").close()
            else:
                train.report({"step": step + 1})
            time.sleep(0.4)

    def killer():
        import os
        deadline = time.monotonic() + 120
        while not os.path.exists(marker):
            if time.monotonic() > deadline:
                return
            time.sleep(0.1)
        cluster.remove_node(nodes[-1])  # kills that worker's process

    try:
        t = threading.Thread(target=killer, daemon=True)
        t.start()
        trainer = JaxTrainer(
            loop, train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(
                num_workers=4, min_workers=1, max_workers=4,
                resources_per_worker={"CPU": 1, "slot": 1}),
            run_config=RunConfig(
                name="elastic", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2)))
        result = trainer.fit()
        t.join(timeout=10)
        assert result.error is None, result.error
        sizes = trainer._controller.world_sizes
        # First gang was 4-wide; after losing a node the elastic policy
        # re-formed the collective at 3.
        assert sizes[0] == 4, sizes
        assert sizes[-1] == 3, sizes
        assert result.metrics["step"] == 6
        assert result.metrics["world"] == 3
        assert result.metrics["coll_sum"] == 3.0
        # Resumed from checkpoint, not from scratch: the state machine
        # went through RESTARTING exactly once.
        states = [s for s, _ in trainer._controller.state_log]
        assert states.count("RESTARTING") == 1, states
    finally:
        cluster.shutdown()
