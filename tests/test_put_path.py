"""Zero-copy put path: reserve -> write-in-place -> seal.

Covers the striped per-client reservation protocol (concurrent
writers, byte-exact readback), the seeded store.put fault contract
(a failed mid-write put frees its reservation and the id is cleanly
retryable), the flag-off zero-work guard (store_zero_copy_put_enabled
=false must take the EXACT legacy staging path), the small-put gate
bypass (puts under host_copy_gate_min_bytes acquire zero HostCopyGate
tickets — counter-proven, perf_smoke style), the raw-bytes fast path
(bytes/bytearray/memoryview skip pickle and keep their type), and the
segment-pool recycle counters.

Runs under BOTH conftest guards (lockdep + refdebug): the 8-thread
writer storm exercises the store lock against the per-stripe pool
locks, and must come out with zero potential-ABBA cycles.
"""

import os
import threading

import numpy as np
import pytest

from ray_tpu._private import fault
from ray_tpu._private import netcomm
from ray_tpu._private import object_store
from ray_tpu._private import serialization
from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStore


@pytest.fixture
def store(tmp_path):
    s = ObjectStore(str(tmp_path / "shm"), capacity=1 << 30)
    yield s
    s.shutdown()


@pytest.fixture
def zero_copy_on():
    prev = bool(ray_config.store_zero_copy_put_enabled)
    ray_config.set("store_zero_copy_put_enabled", True)
    yield
    ray_config.set("store_zero_copy_put_enabled", prev)


class TestStripedConcurrentWriters:
    def test_eight_threads_interleaved_sizes_byte_exact(
            self, store, zero_copy_on):
        """8 writer threads x interleaved sizes (spanning the pool-min
        and gate-min thresholds) put/read/free in a loop; every value
        must read back byte-exact. This is the striped-reservation
        storm: stripe claims, pool recycling, hot mappings, and the
        store lock all interleave."""
        sizes = [4 << 10, 64 << 10, 300 << 10, 1 << 20, 2 << 20]
        errors = []

        def writer(tid):
            try:
                for i in range(12):
                    n = sizes[(tid + i) % len(sizes)]
                    payload = bytes([((tid << 4) | (i & 0xF)) & 0xFF]) * n
                    oid = ObjectID.from_random()
                    store.put_serialized(
                        oid, serialization.serialize(payload))
                    out = store.get(oid)
                    if out != payload:
                        errors.append(
                            f"thread {tid} iter {i}: {n}-byte payload "
                            f"corrupted (got {len(out)} bytes, "
                            f"first={out[:8]!r})")
                    store.free(oid)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(f"thread {tid}: {e!r}")

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        st = store.stats()
        assert st["used_bytes"] == 0
        # The hot loop recycles: with 8 threads re-putting the same
        # five sizes, the pool must have served a healthy share.
        assert st["pool_hits"] > 0

    def test_interleaved_numpy_and_raw_round_trip(
            self, store, zero_copy_on):
        arr = np.arange(1 << 16, dtype=np.int64)
        raw = bytearray(os.urandom(1 << 18))
        for payload in (arr, raw, memoryview(bytes(raw))):
            oid = ObjectID.from_random()
            store.put_serialized(oid, serialization.serialize(payload))
            out = store.get(oid)
            if isinstance(payload, np.ndarray):
                assert np.array_equal(out, payload)
            else:
                assert bytes(out) == bytes(payload)
            store.free(oid)


class TestPutFaultInjection:
    def test_failed_put_frees_reservation_and_retries(
            self, store, zero_copy_on):
        """Seeded store.put fault at the first firing: the put raises,
        the reservation is rolled back (zero used bytes, no partial
        file), and retrying the SAME id succeeds."""
        fault.configure(
            {"seed": 7,
             "rules": [{"site": "store.put", "action": "raise",
                        "at": [0], "exc": "OSError"}]},
            propagate_env=False)
        try:
            oid = ObjectID.from_random()
            payload = b"\xbe" * (1 << 20)
            with pytest.raises(OSError):
                store.put_serialized(
                    oid, serialization.serialize(payload))
            st = store.stats()
            assert st["used_bytes"] == 0, \
                "failed put leaked reservation accounting"
            assert st["num_objects"] == 0
            assert not os.path.exists(store._path(oid)), \
                "failed put left a partial file (truncation hazard)"
            # Retry of the same id (the fault schedule only fires at
            # seq 0) lands cleanly.
            store.put_serialized(oid, serialization.serialize(payload))
            assert store.get(oid) == payload
            store.free(oid)
        finally:
            fault.configure(None, propagate_env=False)

    def test_fault_free_when_disabled(self, store, zero_copy_on):
        oid = ObjectID.from_random()
        store.put_serialized(oid, serialization.serialize(b"x" * 8192))
        assert store.get(oid) == b"x" * 8192
        store.free(oid)


@pytest.mark.perf_smoke
class TestFlagOffZeroWork:
    def test_flag_off_takes_exact_legacy_path(self, store):
        """With the flag off, the in-place machinery must do ZERO work
        (inplace_put_ops must not move) and round trips still hold —
        the legacy write(2) staging path is byte-compatible."""
        prev = bool(ray_config.store_zero_copy_put_enabled)
        ray_config.set("store_zero_copy_put_enabled", False)
        try:
            before = object_store.inplace_put_ops()
            arr = np.arange(50000, dtype=np.float64)
            oid = ObjectID.from_random()
            store.put_serialized(oid, serialization.serialize(arr))
            assert np.array_equal(store.get(oid), arr)
            store.free(oid)
            assert object_store.inplace_put_ops() == before, \
                "flag-off put touched the in-place path"
        finally:
            ray_config.set("store_zero_copy_put_enabled", prev)

    def test_flag_on_counts_inplace_ops(self, store, zero_copy_on):
        before = object_store.inplace_put_ops()
        oid = ObjectID.from_random()
        store.put_serialized(oid, serialization.serialize(b"y" * 8192))
        assert object_store.inplace_put_ops() == before + 1
        store.free(oid)


@pytest.mark.perf_smoke
class TestSmallPutGateBypass:
    def test_small_put_acquires_zero_gate_tickets(
            self, store, zero_copy_on):
        """perf_smoke-style counter guard: drop the gate's size
        threshold so a 64 KiB put WOULD be gated, and prove the
        host_copy_gate_min_bytes floor bypasses ticket acquisition
        entirely (netcomm.gate_ops() must not move)."""
        prev_thresh = float(ray_config.transfer_serialize_threshold_mb)
        prev_min = int(ray_config.host_copy_gate_min_bytes)
        ray_config.set("transfer_serialize_threshold_mb", 0.001)  # 1 KiB
        ray_config.set("host_copy_gate_min_bytes", 256 << 10)
        try:
            before = netcomm.gate_ops()
            oid = ObjectID.from_random()
            store.put_serialized(
                oid, serialization.serialize(b"g" * (64 << 10)))
            assert netcomm.gate_ops() == before, \
                "small put below host_copy_gate_min_bytes took a " \
                "HostCopyGate ticket"
            store.free(oid)
        finally:
            ray_config.set("transfer_serialize_threshold_mb", prev_thresh)
            ray_config.set("host_copy_gate_min_bytes", prev_min)

    def test_big_fresh_put_still_gated(self, store, zero_copy_on):
        """The floor must NOT disable the gate for genuinely large
        fresh-page writes (above both thresholds, nothing pooled)."""
        prev_thresh = float(ray_config.transfer_serialize_threshold_mb)
        ray_config.set("transfer_serialize_threshold_mb", 0.5)
        try:
            before = netcomm.gate_ops()
            oid = ObjectID.from_random()
            store.put_serialized(
                oid, serialization.serialize(b"G" * (1 << 20)))
            assert netcomm.gate_ops() == before + 1
            store.free(oid)
        finally:
            ray_config.set("transfer_serialize_threshold_mb", prev_thresh)

    def test_prefaulted_pool_claim_bypasses_gate(
            self, store, zero_copy_on):
        """A put landing in a pool-recycled (pre-faulted) segment
        skips the gate whatever its size: it allocates no fresh
        pages, which is the only thing the gate meters."""
        prev_thresh = float(ray_config.transfer_serialize_threshold_mb)
        ray_config.set("transfer_serialize_threshold_mb", 0.5)
        payload = b"p" * (2 << 20)
        try:
            oid = ObjectID.from_random()
            store.put_serialized(oid, serialization.serialize(payload))
            store.free(oid)  # -> pool
            before = netcomm.gate_ops()
            oid2 = ObjectID.from_random()
            store.put_serialized(oid2, serialization.serialize(payload))
            assert store.stats()["pool_hits"] >= 1
            assert netcomm.gate_ops() == before, \
                "pool-recycled put took a gate ticket"
            store.free(oid2)
        finally:
            ray_config.set("transfer_serialize_threshold_mb", prev_thresh)


class TestRawBytesFastPath:
    def test_types_preserved_and_payload_out_of_band(self, zero_copy_on):
        """bytes/bytearray/memoryview above the raw threshold skip
        pickle: the meta holds only the reconstructor, the payload
        rides as ONE out-of-band buffer, and deserialization hands
        back the caller's type."""
        for payload, want_type in (
                (b"b" * 8192, bytes),
                (bytearray(b"a" * 8192), bytearray),
                (memoryview(b"m" * 8192), bytes),
                (memoryview(bytearray(b"w" * 8192)), bytearray)):
            sobj = serialization.serialize(payload)
            assert len(sobj.buffers) == 1, \
                f"{type(payload).__name__} payload not out-of-band"
            assert sobj.buffers[0].nbytes == 8192
            out = serialization.deserialize(
                memoryview(sobj.to_bytes()))
            assert type(out) is want_type
            assert bytes(out) == bytes(payload)

    def test_small_bytes_stay_inline(self, zero_copy_on):
        sobj = serialization.serialize(b"tiny")
        assert len(sobj.buffers) == 0

    def test_flag_off_raw_path_disabled(self):
        prev = bool(ray_config.store_zero_copy_put_enabled)
        ray_config.set("store_zero_copy_put_enabled", False)
        try:
            sobj = serialization.serialize(b"b" * 8192)
            assert len(sobj.buffers) == 0, \
                "flag-off serialize took the raw out-of-band path"
        finally:
            ray_config.set("store_zero_copy_put_enabled", prev)


class TestReservationProtocol:
    def test_reserve_seal_read_back(self, store, zero_copy_on):
        oid = ObjectID.from_random()
        res = store.reserve(oid, 4096)
        view = res.view()
        view[:5] = b"hello"
        view.release()
        res.seal()
        raw = store.get_raw(oid)
        assert bytes(raw[:5]) == b"hello"
        raw.release()
        store.free(oid)

    def test_abort_rolls_back_accounting_and_file(
            self, store, zero_copy_on):
        oid = ObjectID.from_random()
        res = store.reserve(oid, 1 << 20)
        assert store.stats()["used_bytes"] == 1 << 20
        res.abort()
        st = store.stats()
        assert st["used_bytes"] == 0
        assert st["num_objects"] == 0
        assert not os.path.exists(store._path(oid))

    def test_pool_counters_and_reclaimed_gauge(self, tmp_path):
        """Capacity pressure drains pooled segments and the reclaimed
        bytes surface on the node-tagged gauge attribute the daemon /
        head heartbeats export."""
        s = ObjectStore(str(tmp_path / "shm2"), capacity=3 << 20)
        prev = bool(ray_config.store_zero_copy_put_enabled)
        ray_config.set("store_zero_copy_put_enabled", True)
        try:
            payload = b"r" * (2 << 20)
            oid = ObjectID.from_random()
            s.put_serialized(oid, serialization.serialize(payload))
            s.free(oid)  # -> pool (2 MiB pooled, capacity 3 MiB)
            assert s.stats()["pool_bytes"] > 0
            # A second 2 MiB put cannot fit alongside the pooled bytes:
            # the pool drains first.
            oid2 = ObjectID.from_random()
            s.put_serialized(oid2, serialization.serialize(payload))
            assert s.pool_reclaimed_bytes > 0
            assert s.stats()["pool_reclaimed_bytes"] > 0
            s.free(oid2)
        finally:
            ray_config.set("store_zero_copy_put_enabled", prev)
            s.shutdown()


@pytest.mark.skipif(
    not os.environ.get("RAY_TPU_TEST_JAX"),
    reason="jax adopt-native landing (set RAY_TPU_TEST_JAX=1; jax "
           "import costs ~2s and the CPU backend is required)")
class TestAdoptNativePut:
    def test_cpu_jax_array_lands_without_host_bounce(self):
        """_to_host adopts a CPU jax array via dlpack: the numpy view
        handed to the serializer ALIASES the device buffer, so the put
        path's single NT copy is the only movement of the bytes."""
        import jax
        import jax.numpy as jnp
        arr = jnp.arange(1024, dtype=jnp.float32)
        host = serialization._to_host(arr)
        assert isinstance(host, np.ndarray)
        assert np.shares_memory(
            host, np.from_dlpack(arr)) or host.base is not None
        sobj = serialization.serialize(arr)
        out = serialization.deserialize(memoryview(sobj.to_bytes()))
        assert np.array_equal(out, np.asarray(arr))
