"""Model-multiplexing tests (reference strategy: serve/tests/
test_multiplex.py — wrapper LRU semantics + e2e model-id routing +
the serve_multiplexed_model_id HTTP header)."""
import asyncio
import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.multiplex import _ModelMultiplexWrapper


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(autouse=True)
def _clean_apps():
    yield
    try:
        for app in {i.get("app") for i in serve.status().values()}:
            if app:
                serve.delete(app)
    except Exception:
        pass


class TestWrapperUnits:
    def _wrapper(self, max_models=2, log=None):
        log = log if log is not None else []

        async def loader(model_id):
            log.append(("load", model_id))
            return {"id": model_id}

        return _ModelMultiplexWrapper(loader, None, max_models), log

    def test_load_caches_and_lru_evicts(self):
        w, log = self._wrapper(max_models=2)

        async def run():
            m1 = await w.load_model("a")
            m2 = await w.load_model("b")
            assert (await w.load_model("a")) is m1  # cached, no reload
            await w.load_model("c")  # evicts b (a was refreshed)
            assert w.model_ids == ["a", "c"]
            await w.load_model("b")  # reload; evicts a
            assert w.model_ids == ["c", "b"]

        asyncio.run(run())
        assert [m for op, m in log if op == "load"] == \
            ["a", "b", "c", "b"]

    def test_eviction_calls_del(self):
        deleted = []

        class Model:
            def __init__(self, mid):
                self.mid = mid

            def __del__(self):
                deleted.append(self.mid)

        async def loader(model_id):
            return Model(model_id)

        w = _ModelMultiplexWrapper(loader, None, 1)

        async def run():
            await w.load_model("x")
            await w.load_model("y")

        asyncio.run(run())
        assert "x" in deleted

    def test_invalid_model_id(self):
        w, _ = self._wrapper()
        with pytest.raises(ValueError):
            asyncio.run(w.load_model(""))

    def test_eviction_del_runs_exactly_once(self):
        import gc
        calls = []

        class Model:
            def __init__(self, mid):
                self.mid = mid

            def __del__(self):
                calls.append(self.mid)

        async def loader(model_id):
            return Model(model_id)

        w = _ModelMultiplexWrapper(loader, None, 1)

        async def run():
            await w.load_model("x")
            await w.load_model("y")

        asyncio.run(run())
        gc.collect()
        # Explicit eviction cleanup must not be doubled by GC.
        assert calls.count("x") == 1

    def test_router_spills_hot_model(self):
        from ray_tpu.serve.handle import _Router
        r = _Router.__new__(_Router)
        import threading
        r._lock = threading.Lock()
        r._replicas = ["r0", "r1"]
        r._inflight = {0: 20, 1: 0}
        r._qlen_base = {}
        r._qlen_ts = {}
        r._model_locations = {"hot": {0}}
        # Warm replica 0 is saturated: the pick must spill to replica 1.
        assert r._pick([0, 1], model_id="hot") == 1
        # Balanced load: stick with the warm holder.
        r._inflight = {0: 2, 1: 0}
        assert r._pick([0, 1], model_id="hot") == 0

    def test_options_copies_share_router(self):
        from ray_tpu.serve.handle import DeploymentHandle
        h = DeploymentHandle("dep")
        h2 = h.options(multiplexed_model_id="m")
        h3 = h2.options(multiplexed_model_id="n")
        assert h._router_cell is h2._router_cell is h3._router_cell
        assert h._lock is h2._lock

    def test_decorator_validates(self):
        with pytest.raises(ValueError):
            serve.multiplexed(max_num_models_per_replica=0)

    def test_two_multiplexed_methods_separate_caches(self):
        from ray_tpu.serve.multiplex import loaded_model_ids

        class Host:
            @serve.multiplexed(max_num_models_per_replica=2)
            async def get_model(self, model_id):
                return ("model", model_id)

            @serve.multiplexed(max_num_models_per_replica=2)
            async def get_tokenizer(self, model_id):
                return ("tok", model_id)

        h = Host()

        async def run():
            m = await h.get_model("x")
            t = await h.get_tokenizer("x")
            assert m == ("model", "x")
            assert t == ("tok", "x")  # NOT the cached model object

        asyncio.run(run())
        assert loaded_model_ids(h) == ["x"]

    def test_note_grace_survives_probe_wipe(self):
        import threading
        import time
        from ray_tpu.serve.handle import _Router
        r = _Router.__new__(_Router)
        r._lock = threading.Lock()
        r._replicas = ["r0", "r1"]
        r._inflight = {0: 0, 1: 0}
        r._qlen_base = {}
        r._qlen_ts = {}
        r._model_locations = {}
        r._model_note_ts = {}
        with r._lock:
            r._note_model_location("big", 0)
        # Emulate the probe-update rule: a fresh note must survive a
        # probe that does not (yet) see the model loaded.
        now = time.monotonic()
        assert now - r._model_note_ts[("big", 0)] < r._MUX_NOTE_GRACE_S
        assert 0 in r._model_locations["big"]


class TestMultiplexE2E:
    def _deploy(self, num_replicas=2, max_models=2):
        @serve.deployment(num_replicas=num_replicas)
        class MultiModel:
            @serve.multiplexed(max_num_models_per_replica=max_models)
            async def get_model(self, model_id: str):
                return {"model": model_id}

            async def __call__(self, req):
                import os
                mid = serve.get_multiplexed_model_id()
                model = await self.get_model(mid)
                return {"served_by": model["model"], "pid": os.getpid()}

        return serve.run(MultiModel.bind(), name="mux_app",
                         route_prefix="/mux")

    def test_model_id_reaches_replica(self):
        handle = self._deploy()
        out = handle.options(multiplexed_model_id="m1").remote(
            None).result(timeout_s=30)
        assert out["served_by"] == "m1"
        out = handle.options(multiplexed_model_id="m2").remote(
            None).result(timeout_s=30)
        assert out["served_by"] == "m2"

    def test_model_affinity_routing(self):
        handle = self._deploy(num_replicas=2)
        h1 = handle.options(multiplexed_model_id="warm")
        # Warm up: first call picks a replica and records the location.
        first = h1.remote(None).result(timeout_s=30)
        pids = {h1.remote(None).result(timeout_s=30)["pid"]
                for _ in range(10)}
        # All subsequent same-model requests stick to the warm replica.
        assert pids == {first["pid"]}

    def test_http_header_path(self):
        self._deploy()
        addr = serve.proxy_address()
        req = urllib.request.Request(
            addr + "/mux", data=b"null", method="POST",
            headers={"serve_multiplexed_model_id": "hdr-model",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            body = json.loads(r.read())
        assert body["served_by"] == "hdr-model"

    def test_no_model_id_means_empty_context(self):
        @serve.deployment
        class Plain:
            def __call__(self, req):
                return {"mid": serve.get_multiplexed_model_id()}

        handle = serve.run(Plain.bind(), name="plain_mux",
                           route_prefix="/plainmux")
        assert handle.remote(None).result(timeout_s=30)["mid"] == ""
