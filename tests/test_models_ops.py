"""Model + ops tests (CPU backend; kernel-vs-reference equivalence is the
test pattern — the TPU kernel path is exercised on hardware by bench.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import (
    GPTConfig,
    gpt_forward,
    gpt_init,
    gpt_loss,
    gpt_param_axes,
    make_train_step,
)
from ray_tpu.models.gpt import shard_batch, shard_params
from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.layers import rms_norm, rope, swiglu
from ray_tpu.parallel import MeshConfig, make_mesh, tp_rules, fsdp_rules


class TestAttention:
    def test_matches_reference(self):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(k1, (2, 4, 64, 32))
        k = jax.random.normal(k2, (2, 4, 64, 32))
        v = jax.random.normal(k3, (2, 4, 64, 32))
        np.testing.assert_allclose(
            np.asarray(flash_attention(q, k, v, True, None)),
            np.asarray(mha_reference(q, k, v, True)),
            rtol=2e-3, atol=2e-3)

    def test_causality(self):
        # Changing future tokens must not change past outputs.
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(k1, (1, 2, 16, 8))
        k = jax.random.normal(k2, (1, 2, 16, 8))
        v = jax.random.normal(k3, (1, 2, 16, 8))
        out1 = flash_attention(q, k, v, True, None)
        k_mod = k.at[:, :, 10:, :].set(99.0)
        v_mod = v.at[:, :, 10:, :].set(99.0)
        out2 = flash_attention(q, k_mod, v_mod, True, None)
        np.testing.assert_allclose(
            np.asarray(out1[:, :, :10]), np.asarray(out2[:, :, :10]),
            rtol=1e-5, atol=1e-5)

    def test_grad_matches_reference(self):
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
        q = jax.random.normal(k1, (1, 2, 32, 16))
        k = jax.random.normal(k2, (1, 2, 32, 16))
        v = jax.random.normal(k3, (1, 2, 32, 16))
        g1 = jax.grad(lambda q_: flash_attention(
            q_, k, v, True, None).sum())(q)
        g2 = jax.grad(lambda q_: mha_reference(
            q_, k, v, True).sum())(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=2e-3, atol=2e-3)


class TestLayers:
    def test_rms_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
        w = jnp.ones((16,))
        out = rms_norm(x, w)
        rms = np.sqrt(np.mean(np.square(np.asarray(out)), axis=-1))
        np.testing.assert_allclose(rms, 1.0, rtol=1e-2)

    def test_rope_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 8, 16))
        out = rope(x)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-4)

    def test_rope_relative(self):
        # RoPE dot products depend only on relative positions.
        x = jnp.ones((1, 1, 4, 8))
        r = rope(x)
        d01 = float(jnp.dot(r[0, 0, 0], r[0, 0, 1]))
        d12 = float(jnp.dot(r[0, 0, 1], r[0, 0, 2]))
        assert abs(d01 - d12) < 1e-4

    def test_swiglu_shapes(self):
        x = jnp.ones((2, 4, 8))
        out = swiglu(x, jnp.ones((8, 16)), jnp.ones((8, 16)),
                     jnp.ones((16, 8)))
        assert out.shape == (2, 4, 8)


class TestGPT:
    def test_forward_shapes(self):
        cfg = GPTConfig.tiny()
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = gpt_forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_loss_decreases(self):
        cfg = GPTConfig.tiny()
        init_state, train_step = make_train_step(cfg)
        state = init_state(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        batch = (tokens, jnp.roll(tokens, -1, axis=1))
        losses = []
        for _ in range(5):
            state, m = train_step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert int(state["step"]) == 5

    def test_param_axes_structure_matches(self):
        cfg = GPTConfig.tiny()
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        axes = gpt_param_axes(cfg)
        leaves, treedef = jax.tree.flatten(params)
        axes_leaves = treedef.flatten_up_to(axes)
        assert len(leaves) == len(axes_leaves)
        for p, ax in zip(leaves, axes_leaves):
            assert p.ndim == len(ax)

    def test_sharded_train_step(self):
        cfg = GPTConfig.tiny()
        mesh = make_mesh(MeshConfig(dp=4, tp=2))
        init_state, train_step = make_train_step(
            cfg, mesh=mesh, rules=tp_rules())
        state = init_state(jax.random.PRNGKey(0))
        spec = state["params"]["layers"][0]["wqkv"].sharding.spec
        assert "tp" in str(spec)
        tokens = np.random.randint(0, cfg.vocab_size, (4, 32),
                                   dtype=np.int32)
        batch = shard_batch((tokens, np.roll(tokens, -1, 1)), mesh)
        state, m = train_step(state, batch)
        assert np.isfinite(float(m["loss"]))

    def test_fsdp_sharding(self):
        cfg = GPTConfig.tiny()
        mesh = make_mesh(MeshConfig(dp=1, fsdp=8, tp=1))
        params = gpt_init(jax.random.PRNGKey(0), cfg)
        sharded = shard_params(params, cfg, mesh, fsdp_rules())
        spec = sharded["layers"][0]["w1"].sharding.spec
        assert "fsdp" in str(spec)

    def test_sharded_matches_unsharded(self):
        cfg = GPTConfig.tiny()
        tokens = np.random.randint(0, cfg.vocab_size, (4, 32),
                                   dtype=np.int32)
        batch = (jnp.asarray(tokens), jnp.asarray(np.roll(tokens, -1, 1)))
        init_state, train_step = make_train_step(cfg, donate=False)
        state = init_state(jax.random.PRNGKey(0))
        _, m1 = train_step(state, batch)

        mesh = make_mesh(MeshConfig(dp=4, tp=2))
        init_state2, train_step2 = make_train_step(
            cfg, mesh=mesh, rules=tp_rules(), donate=False)
        state2 = init_state2(jax.random.PRNGKey(0))
        _, m2 = train_step2(state2, shard_batch(batch, mesh))
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                                   rtol=1e-3)


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__
        fn, args = __graft_entry__.entry()
        out = jax.jit(fn)(*args)
        assert out.ndim == 3

    def test_dryrun_multichip(self):
        import __graft_entry__
        __graft_entry__.dryrun_multichip(8)


class TestFlashKernelInterpret:
    """The actual Pallas kernels (fwd + blockwise flash-2 backward) in
    interpreter mode — the SURVEY §4 CPU-mirror of the on-TPU path."""

    @pytest.fixture(autouse=True)
    def _interpret(self, monkeypatch):
        monkeypatch.setenv("RAY_TPU_PALLAS_INTERPRET", "1")

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_fwd_matches_reference(self, causal):
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (jax.random.normal(kk, (1, 2, 256, 64), jnp.float32)
                   for kk in ks)
        out = flash_attention(q, k, v, causal, None)
        ref = mha_reference(q, k, v, causal)
        # f32 attention has ~1e-2 absolute noise between equivalent
        # formulations at this scale; the kernel must sit in that band.
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_bwd_matches_reference(self, causal):
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        q, k, v = (jax.random.normal(kk, (1, 2, 256, 64), jnp.float32)
                   for kk in ks)

        def loss_k(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal, None) ** 2)

        def loss_r(q, k, v):
            return jnp.sum(mha_reference(q, k, v, causal) ** 2)

        gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gk, gr):
            scale = max(1.0, float(jnp.abs(b).max()))
            np.testing.assert_allclose(
                np.asarray(a) / scale, np.asarray(b) / scale,
                atol=6e-3, rtol=6e-3)

    def test_kernel_uneven_heads_batch(self):
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (jax.random.normal(kk, (3, 5, 128, 32), jnp.float32)
                   for kk in ks)
        out = flash_attention(q, k, v, True, None)
        ref = mha_reference(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-2, rtol=2e-2)
