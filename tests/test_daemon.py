"""Multi-host control plane: per-host daemons, cross-node object
transfer, and node-failure handling.

Reference strategy: python/ray/tests with ray_start_cluster — N real
raylet processes sharing one GCS (cluster_utils.py:135), killed
mid-workload to exercise failover (test_chaos.py RayletKiller,
_private/test_utils.py:1618). Here each `add_node(daemon=True)` is a
REAL subprocess with its own worker pool + shm store, joined over TCP.
"""

import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def daemon_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    a = cluster.add_node(num_cpus=2, resources={"A": 4}, daemon=True)
    b = cluster.add_node(num_cpus=2, resources={"B": 4}, daemon=True)
    yield cluster, a, b
    try:
        cluster.shutdown()
    except Exception:
        pass  # a destructive test later in the module tore it down


def test_remote_dispatch(daemon_cluster):
    @ray.remote(resources={"A": 1})
    def pid():
        import os
        return os.getpid()

    import os
    pids = ray.get([pid.remote() for _ in range(4)])
    assert all(p != os.getpid() for p in pids)


def test_driver_put_consumed_on_daemon(daemon_cluster):
    data = ray.put(np.ones(200_000))

    @ray.remote(resources={"A": 1})
    def consume(a):
        return float(a.sum())

    assert ray.get(consume.remote(data)) == 200_000.0


def test_daemon_to_daemon_transfer(daemon_cluster):
    @ray.remote(resources={"A": 1})
    def produce():
        return np.arange(300_000, dtype=np.float32)

    @ray.remote(resources={"B": 1})
    def total(a):
        return float(a.sum())

    ref = produce.remote()
    expected = float(np.arange(300_000, dtype=np.float32).sum())
    assert ray.get(total.remote(ref)) == expected


def test_daemon_result_pulled_to_driver(daemon_cluster):
    @ray.remote(resources={"B": 1})
    def produce():
        return np.full(250_000, 3.0)

    arr = ray.get(produce.remote())
    assert arr.shape == (250_000,) and arr[0] == 3.0


def test_actor_on_daemon(daemon_cluster):
    @ray.remote(resources={"A": 1})
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]
    ray.kill(c)


def test_nested_submission_from_daemon(daemon_cluster):
    @ray.remote(resources={"B": 1})
    def outer():
        @ray.remote
        def inner():
            return "inner-ok"

        return ray.get(inner.remote())

    assert ray.get(outer.remote()) == "inner-ok"


def test_streaming_generator_on_daemon(daemon_cluster):
    @ray.remote(resources={"A": 1}, num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    out = [ray.get(r) for r in gen.remote(4)]
    assert out == [0, 1, 4, 9]


def test_cluster_resources_include_daemon(daemon_cluster):
    totals = ray.cluster_resources()
    assert totals.get("A", 0) >= 4 and totals.get("B", 0) >= 4


def test_node_sync_gossip_reaches_daemons(daemon_cluster):
    """Bidirectional resource sync (reference: ray_syncer.h — raylets
    and the GCS gossip per-node resource views): every heartbeat is
    ACKed with the head's cluster view, and a worker on a daemon node
    reads that view FROM ITS DAEMON (op local_node_view) without a
    head round trip."""
    cluster, a, b = daemon_cluster

    @ray.remote(resources={"A": 1})
    def view_from_daemon():
        import time

        from ray_tpu._private import state
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            out = state.current().gcs_request("local_node_view")
            if out.get("view") and len(out["view"]) >= 3:
                return out
            time.sleep(0.5)  # next heartbeat carries the sync
        return out

    out = ray.get(view_from_daemon.remote(), timeout=90)
    # Answered by the daemon (its own node id), holding a 3-node view
    # (head + 2 daemons) with per-node resource totals.
    assert out["node_id"] == a.node_id, out
    assert out["ts"] is not None
    nodes = {n["node_id"]: n for n in out["view"]}
    assert len(nodes) >= 3, nodes.keys()
    totals = [n for n in out["view"]
              if n.get("resources_total", {}).get("A")]
    assert totals, out["view"]

    # Head-attached callers get the authoritative view directly.
    from ray_tpu._private import state as _state
    head_view = _state.current().gcs_request("local_node_view")
    assert len(head_view["view"]) >= 3


# -- destructive tests (tear down the shared runtime); keep them LAST ----

def test_daemon_kill_task_retry():
    """Killing a node daemon fails its in-flight tasks through the worker
    death path; retries land on surviving nodes (reference:
    test_chaos.py semantics)."""
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    # Virtual fallback node carries the same resource, so retries have
    # somewhere to go once the daemon dies.
    victim = cluster.add_node(num_cpus=2, resources={"R": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"R": 2})
    try:
        @ray.remote(resources={"R": 1}, max_retries=2)
        def slow():
            import os
            import time
            time.sleep(2.0)
            return os.getpid()

        ref = slow.remote()
        time.sleep(0.7)  # ensure it is running on the daemon
        victim.proc.kill()
        assert isinstance(ray.get(ref, timeout=60), int)
    finally:
        cluster.shutdown()


def test_daemon_kill_actor_restart():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    victim = cluster.add_node(num_cpus=2, resources={"R": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"R": 2})
    try:
        @ray.remote(resources={"R": 1}, max_restarts=1, max_task_retries=1)
        class Sticky:
            def where(self):
                import os
                return os.getpid()

        a = Sticky.remote()
        first = ray.get(a.where.remote(), timeout=60)
        victim.proc.kill()
        time.sleep(1.0)
        second = ray.get(a.where.remote(), timeout=60)
        assert second != first
    finally:
        cluster.shutdown()


def test_object_recovery_after_node_loss():
    """Objects whose primary copy lived on a dead node are reconstructed
    from lineage on the next get (reference: ObjectRecoveryManager,
    object_recovery_manager.h:38)."""
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    victim = cluster.add_node(num_cpus=2, resources={"R": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"R": 2})
    try:
        @ray.remote(resources={"R": 1}, max_retries=2)
        def produce():
            return np.full(200_000, 9.0)

        ref = produce.remote()
        ray.wait([ref], timeout=60)
        victim.proc.kill()
        time.sleep(1.0)
        arr = ray.get(ref, timeout=60)
        assert arr[0] == 9.0 and arr.shape == (200_000,)
    finally:
        cluster.shutdown()
