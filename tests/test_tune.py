"""Tune tests (reference test model: python/ray/tune/tests/ —
test_tune_run, searcher/scheduler suites, experiment restore)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train.checkpoint import Checkpoint


@pytest.fixture(scope="module")
def rt(tmp_path_factory):
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def storage(tmp_path):
    return str(tmp_path)


class TestSearchSpaces:
    def test_grid_and_samples(self):
        from ray_tpu.tune.search import generate_variants
        space = {"a": tune.grid_search([1, 2, 3]),
                 "b": tune.uniform(0.0, 1.0),
                 "c": "fixed"}
        variants = generate_variants(space, num_samples=2, seed=0)
        assert len(variants) == 6
        assert sorted(v["a"] for v in variants) == [1, 1, 2, 2, 3, 3]
        assert all(0.0 <= v["b"] <= 1.0 for v in variants)
        assert all(v["c"] == "fixed" for v in variants)

    def test_domains(self):
        import random
        rng = random.Random(0)
        assert 1 <= tune.randint(1, 10).sample(rng) < 10
        assert tune.choice(["x", "y"]).sample(rng) in ("x", "y")
        v = tune.loguniform(1e-4, 1e-1).sample(rng)
        assert 1e-4 <= v <= 1e-1
        q = tune.quniform(0, 1, 0.25).sample(rng)
        assert q in (0.0, 0.25, 0.5, 0.75, 1.0)

    def test_nested_grid_search_expands(self):
        from ray_tpu.tune.search import generate_variants
        space = {"opt": {"lr": tune.grid_search([0.1, 0.01]),
                         "name": "sgd"},
                 "top": tune.grid_search([1, 2])}
        variants = generate_variants(space, 1, seed=0)
        assert len(variants) == 4
        assert {v["opt"]["lr"] for v in variants} == {0.1, 0.01}
        assert all(v["opt"]["name"] == "sgd" for v in variants)

    def test_sample_from(self):
        from ray_tpu.tune.search import generate_variants
        space = {"a": tune.grid_search([2, 4]),
                 "b": tune.sample_from(lambda spec: spec.config.a * 10)}
        variants = generate_variants(space, 1, seed=0)
        assert {(v["a"], v["b"]) for v in variants} == {(2, 20), (4, 40)}


class TestTunerFit:
    def test_grid_sweep_best_result(self, rt, storage):
        def trainable(config):
            # quadratic with max at x=3
            score = -(config["x"] - 3) ** 2
            tune.report({"score": score})

        tuner = tune.Tuner(
            trainable,
            param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
            tune_config=tune.TuneConfig(metric="score", mode="max"),
            run_config=tune.RunConfig(storage_path=storage))
        grid = tuner.fit()
        assert len(grid) == 5
        assert grid.num_errors == 0
        best = grid.get_best_result()
        assert best.config["x"] == 3
        assert best.metrics["score"] == 0

    def test_multi_iteration_and_stop_condition(self, rt, storage):
        def trainable(config):
            for i in range(100):
                tune.report({"loss": 1.0 / (i + 1)})

        tuner = tune.Tuner(
            trainable, param_space={},
            tune_config=tune.TuneConfig(metric="loss", mode="min"),
            run_config=tune.RunConfig(
                storage_path=storage, stop={"training_iteration": 5}))
        grid = tuner.fit()
        assert grid[0].metrics["training_iteration"] <= 6

    def test_trial_error_surfaces(self, rt, storage):
        def trainable(config):
            if config["x"] == 1:
                raise RuntimeError("boom")
            tune.report({"score": config["x"]})

        grid = tune.Tuner(
            trainable, param_space={"x": tune.grid_search([0, 1])},
            tune_config=tune.TuneConfig(metric="score", mode="max"),
            run_config=tune.RunConfig(storage_path=storage)).fit()
        assert grid.num_errors == 1
        assert "boom" in grid.errors[0]
        assert grid.get_best_result().config["x"] == 0

    def test_checkpoint_report_and_best(self, rt, storage):
        def trainable(config):
            import tempfile
            for i in range(3):
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "it.txt"), "w") as f:
                    f.write(str(i))
                tune.report({"score": i},
                            checkpoint=Checkpoint.from_directory(d))

        grid = tune.Tuner(
            trainable, param_space={},
            tune_config=tune.TuneConfig(metric="score", mode="max"),
            run_config=tune.RunConfig(storage_path=storage)).fit()
        r = grid[0]
        assert r.checkpoint is not None
        with open(os.path.join(r.checkpoint.path, "it.txt")) as f:
            assert f.read() == "2"


class TestSchedulers:
    def test_asha_stops_bad_trials(self, rt, storage):
        def trainable(config):
            for i in range(16):
                tune.report({"score": config["quality"] * (i + 1)})

        # Sequential, best-first: async SHA only cuts a trial when its rung
        # score is outside the top 1/rf of scores recorded so far, so the
        # later (worse) trials stop at the first rung deterministically.
        grid = tune.Tuner(
            trainable,
            param_space={"quality": tune.grid_search([5.0, 2.0, 1.0])},
            tune_config=tune.TuneConfig(
                metric="score", mode="max",
                max_concurrent_trials=1,
                scheduler=tune.ASHAScheduler(
                    max_t=16, grace_period=2, reduction_factor=2)),
            run_config=tune.RunConfig(storage_path=storage)).fit()
        best = grid.get_best_result()
        assert best.config["quality"] == 5.0
        iters = {r.config["quality"]: r.metrics.get("training_iteration", 0)
                 for r in grid}
        assert iters[5.0] == 16          # leader runs to max_t
        assert iters[2.0] < 16           # cut at a rung
        assert iters[1.0] < 16

    def test_median_stopping_rule_unit(self):
        rule = tune.MedianStoppingRule(metric="acc", mode="max",
                                       grace_period=1,
                                       min_samples_required=2)
        from ray_tpu.tune.schedulers import CONTINUE, STOP
        for step in range(1, 4):
            assert rule.on_result("good1", {
                "training_iteration": step, "acc": 0.9}) == CONTINUE
            assert rule.on_result("good2", {
                "training_iteration": step, "acc": 0.8}) == CONTINUE
        assert rule.on_result("bad", {
            "training_iteration": 2, "acc": 0.1}) == STOP

    def test_pbt_exploit_unit(self):
        pbt = tune.PopulationBasedTraining(
            metric="score", mode="max", perturbation_interval=2,
            hyperparam_mutations={"lr": tune.loguniform(1e-4, 1e-1)},
            seed=0)
        pbt.on_result("weak", {"training_iteration": 2, "score": 0.1})
        pbt.on_result("strong", {"training_iteration": 2, "score": 0.9})
        assert pbt.should_perturb("weak", {"training_iteration": 2})
        decision = pbt.exploit_decision(
            "weak", {"weak": {"lr": 1e-3}, "strong": {"lr": 1e-2}})
        assert decision is not None
        src, cfg = decision
        assert src == "strong"
        assert "lr" in cfg
        # top trial never exploits
        assert pbt.exploit_decision(
            "strong", {"weak": {"lr": 1e-3}, "strong": {"lr": 1e-2}}) is None


class TestRestore:
    def test_tuner_restore_completes_unfinished(self, rt, storage):
        def trainable(config):
            tune.report({"score": config["x"]})

        exp = "restore_exp"
        tuner = tune.Tuner(
            trainable, param_space={"x": tune.grid_search([1, 2, 3])},
            tune_config=tune.TuneConfig(metric="score", mode="max"),
            run_config=tune.RunConfig(name=exp, storage_path=storage))
        grid = tuner.fit()
        assert len(grid) == 3
        # Simulate an interruption: rewrite one trial as still PENDING.
        import json
        state_path = os.path.join(storage, exp, "tuner_state.json")
        with open(state_path) as f:
            state = json.load(f)
        state["trials"][1]["state"] = "PENDING"
        state["trials"][1]["last_result"] = {}
        with open(state_path, "w") as f:
            json.dump(state, f)
        grid2 = tune.Tuner.restore(
            os.path.join(storage, exp), trainable).fit()
        assert len(grid2) == 3
        assert grid2.num_errors == 0
        assert grid2.get_best_result().config["x"] == 3


class TestClassTrainable:
    def test_class_api(self, rt, storage):
        class MyTrainable(tune.Trainable):
            def setup(self, config):
                self.x = config["x"]
                self.total = 0

            def step(self):
                self.total += self.x
                return {"total": self.total,
                        "done": self.training_iteration >= 2}

            def save_checkpoint(self, d):
                with open(os.path.join(d, "t.txt"), "w") as f:
                    f.write(str(self.total))
                return d

        grid = tune.Tuner(
            MyTrainable, param_space={"x": tune.grid_search([1, 10])},
            tune_config=tune.TuneConfig(metric="total", mode="max"),
            run_config=tune.RunConfig(storage_path=storage)).fit()
        best = grid.get_best_result()
        assert best.config["x"] == 10
        assert best.metrics["total"] == 30

    def test_with_parameters_class(self, rt, storage):
        class P(tune.Trainable):
            def setup(self, config, bonus=0):
                self.v = config["x"] + bonus

            def step(self):
                return {"v": self.v, "done": True}

        bound = tune.with_parameters(P, bonus=100)
        grid = tune.Tuner(
            bound, param_space={"x": tune.grid_search([1, 2])},
            tune_config=tune.TuneConfig(metric="v", mode="max"),
            run_config=tune.RunConfig(storage_path=storage)).fit()
        assert grid.get_best_result().metrics["v"] == 102
