"""RLlib long tail: schedules, curriculum, self-play league, OPE breadth.

Reference: rllib/utils/schedules/, env_task_fn curriculum, the
self-play/league examples (policies_to_train + snapshot promotion), and
offline/estimators/ (WIS/DM/DR beyond IS).
"""

import numpy as np
import pytest

from ray_tpu.rllib.utils.schedules import (ConstantSchedule,
                                           ExponentialSchedule,
                                           LinearSchedule,
                                           PiecewiseSchedule, Scheduler)


class TestSchedules:
    def test_linear(self):
        s = LinearSchedule(100, final_p=0.0, initial_p=1.0)
        assert s.value(0) == 1.0
        assert abs(s.value(50) - 0.5) < 1e-9
        assert s.value(1000) == 0.0

    def test_piecewise_and_scheduler_formats(self):
        s = PiecewiseSchedule([(0, 1.0), (10, 0.0)])
        assert abs(s.value(5) - 0.5) < 1e-9
        assert s.value(99) == 0.0
        assert Scheduler(0.3).value(1e9) == 0.3
        sch = Scheduler([[0, 1.0], [100, 0.1]])
        assert abs(sch.value(50) - 0.55) < 1e-9

    def test_exponential_and_constant(self):
        assert ConstantSchedule(2.5).value(123) == 2.5
        e = ExponentialSchedule(10, initial_p=1.0, decay_rate=0.1)
        assert abs(e.value(10) - 0.1) < 1e-9


def test_lr_schedule_traces_into_learner():
    from ray_tpu.rllib.core.learner import JaxLearner
    from ray_tpu.rllib.core.rl_module import PPOModule
    from ray_tpu.rllib.algorithms.ppo import make_ppo_loss

    module = PPOModule(4, 2, (8,))
    learner = JaxLearner(module, make_ppo_loss(),
                         lr=[[0, 1e-3], [100, 1e-5]], use_mesh=False)
    batch = {"obs": np.zeros((8, 4), np.float32),
             "actions": np.zeros(8, np.int64),
             "action_logp": np.full(8, -0.69, np.float32),
             "advantages": np.ones(8, np.float32),
             "value_targets": np.zeros(8, np.float32)}
    stats = learner.update(batch)
    assert np.isfinite(stats["total_loss"])


class _TaskEnv:
    """Task-settable env: obs dim 2, the task scales the reward."""

    def __init__(self, config=None):
        import gymnasium as gym
        self.observation_space = gym.spaces.Box(-1, 1, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self.task = 1
        self._t = 0

    def set_task(self, task):
        self.task = task

    def close(self):
        pass

    def reset(self, seed=None):
        self._t = 0
        return np.zeros(2, np.float32), {}

    def step(self, action):
        self._t += 1
        done = self._t >= 8
        return (np.zeros(2, np.float32), float(self.task), done, False,
                {})


def test_curriculum_env_task_fn_advances(shutdown_only):
    import ray_tpu
    from ray_tpu.rllib import PPOConfig

    ray_tpu.init(num_cpus=2)
    seen = []

    def task_fn(result, cur):
        # Advance the task every iteration (a deterministic curriculum).
        nxt = (cur or 1) + 1
        seen.append(nxt)
        return nxt

    config = (PPOConfig()
              .environment(_TaskEnv, env_task_fn=task_fn)
              .env_runners(num_env_runners=1, rollout_fragment_length=16)
              .training(minibatch_size=8, num_epochs=1)
              .debugging(seed=0))
    algo = config.build()
    r1 = algo.train()
    r2 = algo.train()
    assert r1["env_task"] == 2 and r2["env_task"] == 3
    # The RUNNERS' envs actually switched task: task-2 rewards (2.0/step)
    # appear in iteration 2's samples via episode returns.
    assert r2["episode_return_mean"] > r1["episode_return_mean"]
    algo.stop()


def test_dqn_epsilon_schedule_format(shutdown_only):
    import ray_tpu
    from ray_tpu.rllib import DQNConfig

    ray_tpu.init(num_cpus=2)
    config = (DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=1, rollout_fragment_length=32)
              .training(train_batch_size=32,
                        epsilon=[[0, 1.0], [64, 0.02]],
                        learning_starts=32, updates_per_iter=1)
              .debugging(seed=0))
    algo = config.build()
    r1 = algo.train()
    for _ in range(3):
        r = algo.train()
    # 4 iters x 32 steps >= 64 scheduled steps: epsilon annealed to min.
    assert r1["epsilon"] > r["epsilon"]
    assert abs(r["epsilon"] - 0.02) < 1e-6
    algo.stop()


class TestOPEEstimators:
    def _fragments(self):
        rng = np.random.default_rng(0)
        frags = []
        for _ in range(4):
            n = 12
            frags.append({
                "obs": rng.normal(size=(n, 3)).astype(np.float32),
                "actions": rng.integers(0, 2, n),
                "rewards": np.ones(n, np.float32),
                "terminateds": np.array([False] * (n - 1) + [True]),
                "truncateds": np.zeros(n, bool),
                "action_logp": np.full(n, np.log(0.5), np.float32),
            })
        return frags

    def test_wis_matches_is_for_identical_policies(self):
        from ray_tpu.rllib.offline import (
            ImportanceSamplingEstimator,
            WeightedImportanceSamplingEstimator)
        frags = self._fragments()

        def same_logp(obs, actions):
            return np.full(len(actions), np.log(0.5))

        is_v = ImportanceSamplingEstimator(gamma=1.0).estimate(
            frags, same_logp)
        wis_v = WeightedImportanceSamplingEstimator(gamma=1.0).estimate(
            frags, same_logp)
        # Behavior == target: both must equal the empirical return (12).
        assert abs(is_v["v_target"] - 12.0) < 1e-6
        assert abs(wis_v["v_target"] - 12.0) < 1e-6

    def test_dm_and_dr_with_perfect_model(self):
        from ray_tpu.rllib.offline import (DirectMethodEstimator,
                                           DoublyRobustEstimator)
        frags = self._fragments()
        horizon = 12

        def q_fn(obs):
            # Perfect Q for reward-1-per-step, gamma=1, fixed horizon
            # (approximation: remaining steps unknown -> use horizon).
            return np.full((len(obs), 2), float(horizon))

        def probs_fn(obs):
            return np.full((len(obs), 2), 0.5)

        dm = DirectMethodEstimator(gamma=1.0).estimate(
            frags, q_fn, probs_fn)
        assert abs(dm["v_target"] - horizon) < 1e-6
        dr = DoublyRobustEstimator(gamma=1.0).estimate(
            frags, q_fn, probs_fn,
            target_logp_fn=lambda o, a: np.full(len(a), np.log(0.5)))
        # DR corrects the model's residuals with on-data rewards; with
        # matched policies it stays near the true value.
        assert abs(dr["v_target"] - horizon) < 1.5


def test_self_play_league_promotes_and_freezes(shutdown_only):
    import ray_tpu
    from ray_tpu.rllib.algorithms.multi_agent_ppo import MultiAgentPPOConfig
    from ray_tpu.rllib.env.multi_agent import MultiAgentEnv
    from ray_tpu.rllib.utils.self_play import SelfPlayLeague

    class DuelEnv(MultiAgentEnv):
        def __init__(self, config=None):
            self.agents = ["p0", "p1"]
            self._t = 0

        def reset(self, seed=None):
            self._t = 0
            obs = {a: np.zeros(2, np.float32) for a in self.agents}
            return obs, {}

        def step(self, action_dict):
            self._t += 1
            done = self._t >= 6
            obs = {a: np.zeros(2, np.float32) for a in self.agents}
            rew = {"p0": float(action_dict.get("p0", 0)),
                   "p1": 0.0}
            dones = {"__all__": done}
            return obs, rew, dones, {"__all__": False}, {}

    ray_tpu.init(num_cpus=2)
    config = (MultiAgentPPOConfig()
              .environment(DuelEnv)
              .env_runners(num_env_runners=1, rollout_fragment_length=12)
              .training(minibatch_size=6, num_epochs=1)
              .multi_agent(
                  policies={"main": (2, 2), "opponent": (2, 2)},
                  policy_mapping_fn=lambda aid: ("main" if aid == "p0"
                                                 else "opponent"),
                  policies_to_train=["main"])
              .debugging(seed=0))
    algo = config.build()
    league = SelfPlayLeague(main="main", opponent="opponent",
                            win_rate_threshold=0.5, seed=0)
    league.bootstrap(algo)
    frozen_before = algo.learners["opponent"].get_weights()
    algo.train()
    # policies_to_train froze the opponent: identical weights after.
    import jax
    a = np.concatenate([np.ravel(x) for x in
                        jax.tree_util.tree_leaves(frozen_before)])
    b = np.concatenate([np.ravel(x) for x in jax.tree_util.tree_leaves(
        algo.learners["opponent"].get_weights())])
    np.testing.assert_allclose(a, b)
    stats = league.update(algo, win_rate=0.9)
    assert stats["promoted_this_iter"] and stats["league_size"] >= 2
    stats2 = league.update(algo, win_rate=0.1)
    assert not stats2["promoted_this_iter"]
    algo.stop()
