"""Scalability-envelope invariants at CI scale.

Reference commits these limits for a single node
(release/benchmarks/README.md:27-31): many object args to one task,
thousands of returns, many-object gets, deep task queues, and
multi-GiB objects. bench.py measures them at full scale; these tests
pin the INVARIANTS (they work at all, results are correct) at a scale
that stays fast in-suite.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _runtime():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield


def test_thousand_object_args_single_task():
    @ray_tpu.remote
    def many(*args):
        return sum(args)

    refs = [ray_tpu.put(i) for i in range(1000)]
    assert ray_tpu.get(many.remote(*refs), timeout=120) == sum(range(1000))


def test_five_hundred_returns():
    @ray_tpu.remote(num_returns=500)
    def gen():
        return tuple(range(500))

    out = ray_tpu.get(list(gen.remote()), timeout=120)
    assert out == list(range(500))


def test_two_thousand_object_get_ordered():
    refs = [ray_tpu.put(np.full(10, i)) for i in range(2000)]
    vals = ray_tpu.get(refs, timeout=120)
    assert all(int(v[0]) == i for i, v in enumerate(vals))


def test_ten_thousand_queued_tasks():
    @ray_tpu.remote
    def one():
        return 1

    refs = [one.remote() for _ in range(10000)]
    assert sum(ray_tpu.get(refs, timeout=300)) == 10000


def test_one_gib_object_roundtrip():
    big = np.arange(1 << 27, dtype=np.uint8)  # 128 MiB pattern x checks
    ref = ray_tpu.put(big)
    got = ray_tpu.get(ref)
    assert got.nbytes == big.nbytes
    assert got[12345] == big[12345] and got[-1] == big[-1]
