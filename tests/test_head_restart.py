"""Head fault tolerance: SIGKILL the head mid-workload, restart it on
the same storage path, and the SAME driver session + node daemons
continue (VERDICT r2 #2 done-when).

Reference strategy: src/ray/gcs/gcs_client/test/
gcs_client_reconnection_test.cc — kill/restart the GCS server while
clients hold live channels; clients reconnect with backoff, raylets
re-register, in-flight RPCs fail with a typed error.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from ray_tpu.exceptions import HeadConnectionError
from ray_tpu.util.client import connect


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


TOKEN = "ab" * 16


def _head_env(storage, head_port):
    env = dict(os.environ)
    env.update({
        "RAY_TPU_CLUSTER_TOKEN_HEX": TOKEN,
        "RAY_TPU_GCS_STORAGE_PATH": storage,
        "RAY_TPU_HEAD_PORT": str(head_port),
        "JAX_PLATFORMS": "cpu",
    })
    return env


def _start_head(storage, head_port, client_port):
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
         "--host", "127.0.0.1", "--port", str(client_port),
         "--dashboard-port", "0", "--num-cpus", "2"],
        env=_head_env(storage, head_port),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return proc


def _connect_with_retry(client_port, deadline_s=90):
    deadline = time.monotonic() + deadline_s
    last = None
    while time.monotonic() < deadline:
        try:
            return connect(f"127.0.0.1:{client_port}", token=TOKEN)
        except Exception as e:  # noqa: BLE001 — head still booting
            last = e
            time.sleep(0.5)
    raise RuntimeError(f"head never came up: {last}")


def test_head_sigkill_restart_same_session(tmp_path):
    storage = str(tmp_path / "gcs.sqlite")
    head_port = _free_port()
    client_port = _free_port()

    head = _start_head(storage, head_port, client_port)
    daemon = None
    conn = None
    try:
        conn = _connect_with_retry(client_port)

        # A node daemon joins with reconnect enabled (production join
        # mode semantics).
        denv = dict(os.environ)
        denv.update({
            "RAY_TPU_CLUSTER_TOKEN_HEX": TOKEN,
            "RAY_TPU_HEAD_RECONNECT_ATTEMPTS": "60",
            "JAX_PLATFORMS": "cpu",
        })
        daemon = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.daemon",
             "--address", f"127.0.0.1:{head_port}",
             "--num-cpus", "2", "--resources", '{"W": 2}'],
            env=denv, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

        def n_alive_nodes():
            return sum(1 for n in conn.api_call("list_nodes")
                       if n.get("alive", True))

        deadline = time.monotonic() + 60
        while n_alive_nodes() < 2 and time.monotonic() < deadline:
            time.sleep(0.5)
        assert n_alive_nodes() == 2, "daemon never joined"

        # Workload works pre-crash, on both nodes.
        def sq(x):
            return x * x

        f = conn.remote(sq)
        assert conn.get(f.remote(7)) == 49
        assert conn.get(f.options(resources={"W": 1}).remote(8)) == 64

        # -- SIGKILL the head MID-workload -----------------------------
        def slow(x):
            import time as _t
            _t.sleep(30)
            return x

        g = conn.remote(slow)
        inflight = g.remote(1)
        time.sleep(1.0)
        head.send_signal(signal.SIGKILL)
        head.wait(timeout=10)

        # The restarted head binds the same ports + storage.
        head2 = _start_head(storage, head_port, client_port)
        try:
            # In-flight get fails with the TYPED error (the client
            # reconnects underneath).
            with pytest.raises(HeadConnectionError):
                conn.get(inflight, timeout=120)

            # SAME session continues without re-init: the replayed
            # registration makes f usable again.
            assert conn.get(f.remote(9)) == 81

            # The daemon rejoined the restarted head and still serves
            # its resources.
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                try:
                    if n_alive_nodes() >= 2:
                        break
                except Exception:
                    pass
                time.sleep(0.5)
            assert n_alive_nodes() >= 2, "daemon did not rejoin"
            assert conn.get(
                f.options(resources={"W": 1}).remote(12)) == 144
        finally:
            head2.send_signal(signal.SIGTERM)
            try:
                head2.wait(timeout=10)
            except subprocess.TimeoutExpired:
                head2.kill()
    finally:
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        for proc in (daemon, head):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
