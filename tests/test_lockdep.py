"""Lockdep (runtime lock-order tracker) suite: seeded ABBA detection
with both stacks, clean consistent-order runs, the hold watchdog, and
the zero-work disabled path (perf_smoke, counter-based — the same
guard pattern as the telemetry plane's)."""

import os
import threading

import pytest

from ray_tpu._private import lockdep


@pytest.fixture(autouse=True)
def _fresh_lockdep():
    prev = lockdep.enabled
    lockdep.reset()
    yield
    lockdep.configure(prev, propagate_env=False)
    lockdep.reset()


def _in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(10.0)
    assert not t.is_alive()


def test_seeded_abba_detected_with_both_stacks():
    """Two threads acquiring (A then B) and (B then A) SEQUENTIALLY —
    no actual race needed (the lockdep property) — produce exactly one
    cycle report carrying the stacks of both conflicting
    acquisitions."""
    lockdep.configure(True, propagate_env=False)
    a = lockdep.lock("t.A")
    b = lockdep.lock("t.B")

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    _in_thread(order_ab)
    _in_thread(order_ba)
    reports = lockdep.cycle_reports()
    assert len(reports) == 1
    rep = reports[0]
    assert set(rep["cycle"]) == {"t.A", "t.B"}
    # Both stacks of the closing edge, plus both stacks of the edge
    # that established the reverse order.
    for key in ("stack_a", "stack_b", "reverse_stack_a",
                "reverse_stack_b"):
        assert "test_lockdep.py" in rep[key], (key, rep[key])
    assert rep["stack_b"].count("order_ba")
    assert rep["reverse_stack_b"].count("order_ab")
    # The human-readable rendering names the cycle.
    text = lockdep.format_reports()
    assert "POTENTIAL ABBA DEADLOCK" in text
    assert "t.A" in text and "t.B" in text


def test_consistent_order_is_clean():
    lockdep.configure(True, propagate_env=False)
    a = lockdep.lock("c.A")
    b = lockdep.lock("c.B")

    def ab():
        with a:
            with b:
                pass

    for _ in range(5):
        _in_thread(ab)
    assert lockdep.cycle_reports() == []


def test_three_lock_cycle_detected():
    """A->B, B->C, then C->A closes a 3-cycle (not just direct ABBA)."""
    lockdep.configure(True, propagate_env=False)
    locks = {n: lockdep.lock(f"tri.{n}") for n in "ABC"}

    def pair(x, y):
        def go():
            with locks[x]:
                with locks[y]:
                    pass
        return go

    _in_thread(pair("A", "B"))
    _in_thread(pair("B", "C"))
    assert lockdep.cycle_reports() == []
    _in_thread(pair("C", "A"))
    reports = lockdep.cycle_reports()
    assert len(reports) == 1
    assert set(reports[0]["cycle"]) == {"tri.A", "tri.B", "tri.C"}


def test_rlock_reentrancy_not_a_cycle():
    lockdep.configure(True, propagate_env=False)
    r = lockdep.rlock("re.R")
    other = lockdep.lock("re.O")

    def go():
        with r:
            with r:        # reentrant: no ordering info
                with other:
                    pass
    _in_thread(go)
    assert lockdep.cycle_reports() == []


def test_condition_wait_tracks_release_and_reacquire():
    lockdep.configure(True, propagate_env=False)
    cond = lockdep.condition("cv.C")
    other = lockdep.lock("cv.O")

    def waiter():
        with cond:
            cond.wait(timeout=0.05)
            # Re-acquired after the timed-out wait; taking another lock
            # records the edge without error.
            with other:
                pass
    _in_thread(waiter)
    assert lockdep.cycle_reports() == []


def test_hold_watchdog_flags_long_hold(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKDEP_HOLD_S", "0.02")
    lockdep.configure(True, propagate_env=False)
    lk = lockdep.lock("hold.L")

    import time

    def go():
        with lk:
            time.sleep(0.08)
    _in_thread(go)
    holds = lockdep.hold_reports()
    assert len(holds) == 1
    assert holds[0]["name"] == "hold.L"
    assert holds[0]["held_s"] >= 0.02
    # Watchdog reports are advisory: NOT in the cycle (failure) set.
    assert lockdep.cycle_reports() == []


@pytest.mark.perf_smoke
def test_disabled_path_does_zero_lockdep_work():
    """fault.py/telemetry.py discipline: disabled, the factory returns
    PLAIN threading primitives (no wrapper in the acquire path at all)
    and the instrumentation-op counter stays untouched — counter-based,
    never wall-clock."""
    lockdep.configure(False, propagate_env=False)
    lk = lockdep.lock("off.L")
    rl = lockdep.rlock("off.R")
    cv = lockdep.condition("off.C")
    assert type(lk) is type(threading.Lock())
    assert type(rl) is type(threading.RLock())
    assert type(cv) is threading.Condition
    before = lockdep.instrument_ops()
    for _ in range(2000):
        with lk:
            pass
        with rl:
            pass
    with cv:
        cv.notify_all()
    assert lockdep.instrument_ops() == before


def test_condition_is_reentrant_like_production(monkeypatch):
    """Diagnostic mode must OBSERVE, not change, lock semantics:
    threading.Condition() defaults to an RLock, so the tracked
    condition must too — a reentrant hold that is legal in production
    must not deadlock only under lockdep."""
    lockdep.configure(True, propagate_env=False)
    cond = lockdep.condition("re.cond")
    with cond:
        with cond:          # reentrant: deadlocks on a plain Lock
            pass
        # wait() must drop the WHOLE recursion and restore it.
        with cond:
            cond.wait(timeout=0.01)
    assert lockdep.cycle_reports() == []


def test_configure_off_stops_tracking_existing_wrappers():
    """configure(False) halts recording immediately even for wrappers
    created while enabled (stale per-thread holds still pop cleanly,
    so re-enabling can't see fabricated edges)."""
    lockdep.configure(True, propagate_env=False)
    a = lockdep.lock("late.A")
    b = lockdep.lock("late.B")
    lockdep.configure(False, propagate_env=False)
    ops = lockdep.instrument_ops()

    def ba():
        with b:
            with a:
                pass
    _in_thread(ba)
    assert lockdep.instrument_ops() == ops
    # The reverse order was never recorded, so re-enabling and running
    # the consistent order reports nothing.
    lockdep.configure(True, propagate_env=False)

    def ab():
        with a:
            with b:
                pass
    _in_thread(ab)
    assert lockdep.cycle_reports() == []


def test_child_process_cycles_collected_via_dump_dir(tmp_path,
                                                     monkeypatch):
    """Cycles recorded in spawned processes (which die with their
    in-memory reports) surface through RAY_TPU_LOCKDEP_DIR — the
    channel the conftest guard asserts over for the whole tree."""
    import subprocess
    import sys
    import textwrap

    dump = str(tmp_path)
    env = dict(os.environ, RAY_TPU_LOCKDEP="1",
               RAY_TPU_LOCKDEP_DIR=dump,
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    child = textwrap.dedent("""\
        import threading
        from ray_tpu._private import lockdep
        a = lockdep.lock("child.A"); b = lockdep.lock("child.B")
        def ab():
            with a:
                with b: pass
        def ba():
            with b:
                with a: pass
        for fn in (ab, ba):
            t = threading.Thread(target=fn); t.start(); t.join()
        assert len(lockdep.cycle_reports()) == 1
    """)
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    reports = lockdep.collect_dumped_cycles(dump)
    assert len(reports) == 1
    assert set(reports[0]["cycle"]) == {"child.A", "child.B"}
    assert reports[0]["pid"] != os.getpid()


def test_env_propagation_to_children():
    # Save/restore: an operator-provided RAY_TPU_LOCKDEP=1 in the
    # outer environment must survive this test (later suites' spawned
    # daemons read it).
    prev = os.environ.get("RAY_TPU_LOCKDEP")
    try:
        lockdep.configure(True)
        assert os.environ.get("RAY_TPU_LOCKDEP") == "1"
        lockdep.configure(False)
        assert "RAY_TPU_LOCKDEP" not in os.environ
    finally:
        if prev is not None:
            os.environ["RAY_TPU_LOCKDEP"] = prev
        else:
            os.environ.pop("RAY_TPU_LOCKDEP", None)
