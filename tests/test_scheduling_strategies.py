"""Scheduling strategies: SPREAD, NodeAffinity (hard/soft), node labels.

Reference strategy: python/ray/tests/test_scheduling_2.py (node
affinity + spread placement assertions over a ray_start_cluster) and
src/ray/raylet/scheduling/policy/{spread,node_affinity,node_label}_
scheduling_policy.cc semantics: SPREAD round-robins over feasible
nodes, hard affinity to a gone node fails fast, soft affinity falls
back, hard labels that no node matches fail fast.
"""

import time

import pytest

import ray_tpu as ray
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import TaskUnschedulableError
from ray_tpu.util.scheduling_strategies import (
    In, NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy)


@pytest.fixture(scope="module")
def strategy_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    a = cluster.add_node(num_cpus=2, labels={"zone": "us-east", "disk": "ssd"},
                         daemon=True)
    b = cluster.add_node(num_cpus=2, labels={"zone": "us-west"}, daemon=True)
    yield cluster, a, b
    try:
        cluster.shutdown()
    except Exception:
        pass


@ray.remote
def where():
    return ray.get_runtime_context().get_node_id()


def test_invalid_strategy_rejected_at_options_time():
    @ray.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="Invalid scheduling_strategy"):
        f.options(scheduling_strategy="PACK")
    with pytest.raises(ValueError, match="Invalid scheduling_strategy"):
        f.options(scheduling_strategy=object())


def test_spread_round_robins_over_nodes(strategy_cluster):
    cluster, a, b = strategy_cluster
    # Sequential SPREAD tasks must rotate over all three nodes (head +
    # two daemons), not pile onto the head like DEFAULT does.
    nodes = set(ray.get([
        where.options(scheduling_strategy="SPREAD").remote()
        for _ in range(9)]))
    assert {a.node_id, b.node_id} <= nodes, nodes


def test_default_prefers_head(strategy_cluster):
    cluster, a, b = strategy_cluster
    head_hex = cluster.head_node.node_id
    # Sequential, so head capacity is free for each call; in-suite,
    # leftovers from other modules can hold a head CPU, so require a
    # head MAJORITY rather than unanimity (spill is legitimate when the
    # head is occupied — hybrid policy semantics).
    got = [ray.get(where.remote()) for _ in range(4)]
    assert got.count(head_hex) >= 3, got


def test_node_affinity_hard(strategy_cluster):
    cluster, a, b = strategy_cluster
    for target in (a, b):
        got = ray.get([
            where.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=target.node_id, soft=False)).remote()
            for _ in range(3)])
        assert got == [target.node_id] * 3


def test_node_affinity_to_unknown_node_fails_fast(strategy_cluster):
    ref = where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="f" * 32, soft=False)).remote()
    t0 = time.monotonic()
    with pytest.raises(TaskUnschedulableError, match="unknown"):
        ray.get(ref, timeout=30)
    assert time.monotonic() - t0 < 10  # fail fast, no grace parking


def test_node_affinity_soft_falls_back(strategy_cluster):
    got = ray.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id="f" * 32, soft=True)).remote())
    assert got  # ran somewhere


def test_node_labels_hard(strategy_cluster):
    cluster, a, b = strategy_cluster
    got = ray.get([
        where.options(scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"zone": In("us-east")})).remote()
        for _ in range(3)])
    assert got == [a.node_id] * 3
    # Plain-value shorthand and Exists-free key both match.
    got = ray.get(where.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"zone": "us-west"})).remote())
    assert got == b.node_id


def test_node_labels_soft_preference(strategy_cluster):
    cluster, a, b = strategy_cluster
    got = ray.get(where.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={}, soft={"disk": "ssd"})).remote())
    assert got == a.node_id


def test_node_labels_unmatchable_fails_fast(strategy_cluster):
    ref = where.options(
        scheduling_strategy=NodeLabelSchedulingStrategy(
            hard={"zone": In("mars")})).remote()
    with pytest.raises(TaskUnschedulableError, match="no alive node"):
        ray.get(ref, timeout=30)


def test_affinity_to_dead_node_fails_fast(strategy_cluster):
    """VERDICT r2 #3 done-when: affinity to a DEAD node fails with the
    documented error (runs last: removes node b)."""
    cluster, a, b = strategy_cluster
    target_hex = b.node_id
    cluster.remove_node(b)
    ref = where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=target_hex, soft=False)).remote()
    t0 = time.monotonic()
    with pytest.raises(TaskUnschedulableError, match="dead"):
        ray.get(ref, timeout=30)
    assert time.monotonic() - t0 < 10
    # Soft affinity to the same dead node still completes elsewhere.
    got = ray.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=target_hex, soft=True)).remote())
    assert got != target_hex
