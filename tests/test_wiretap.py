"""Wire-protocol conformance tap (_private/wiretap.py).

Unit half: per-connection SessionDFA interpreters fed synthetic frames
— legal sequences come out clean, injected out-of-order frames are
flagged with both endpoints' recent-frame context, and a
SIGKILL-truncated journal is tolerated by the checker. Dynamic half:
a small cluster under RAY_TPU_WIRETAP=1 journals zero violations (the
protocol-heavy suites run under the conftest guard; this is the
in-file smoke), and the disabled path does ZERO instrumentation work,
proven by the ops counter (the lockdep/refdebug perf_smoke pattern).
"""

import json
import os

import pytest

import ray_tpu
from ray_tpu._private import protocol as P
from ray_tpu._private import wiretap

# An 11-slot compact ACTOR_CALL tuple (slot 0 is the task id — the
# pairing/stream key the extractor pulls).
_CALL = lambda tid: {"c": (tid,) + (None,) * 10}  # noqa: E731


@pytest.fixture
def tap():
    """The tap enabled in-process only: no env propagation, no journal
    dir — violations land in the in-memory list."""
    prev = wiretap.enabled
    prev_dir = os.environ.pop("RAY_TPU_WIRETAP_DIR", None)
    wiretap.reset()
    wiretap.configure(True, propagate_env=False)
    yield wiretap
    wiretap.reset()
    wiretap.configure(prev, propagate_env=False)
    if prev_dir is not None:
        os.environ["RAY_TPU_WIRETAP_DIR"] = prev_dir


# ---------------------------------------------------------------------------
# DFA unit tests (synthetic frames, no cluster)
# ---------------------------------------------------------------------------
def test_legal_direct_sequence_is_clean(tap):
    """call -> result (and a staged serve body freed after use) is the
    contract; the tap must not cry wolf on it."""
    tap.frame("direct", "caller", "c1", "send", P.ACTOR_CALL,
              _CALL(b"t1"))
    tap.frame("direct", "caller", "c1", "recv", P.ACTOR_RESULT,
              {"t": b"t1"})
    tap.frame("direct", "caller", "c1", "send", P.SERVE_REQ,
              {"r": b"r1", "b": ("o", b"oid1")})
    tap.frame("direct", "caller", "c1", "recv", P.SERVE_RESP,
              {"r": b"r1", "v": ("i", b"inline")})
    tap.frame("direct", "caller", "c1", "recv", P.SERVE_BODY_FREE,
              {"o": b"oid1"})
    assert tap.violations() == []


def test_out_of_order_result_flagged_with_context(tap):
    """An ACTOR_RESULT for a task never called is a
    response-without-request; the violation record carries the
    connection's recent-frame ring so a report shows what this
    endpoint sent AND what the peer did."""
    tap.frame("direct", "caller", "c1", "send", P.ACTOR_CALL,
              _CALL(b"t1"))
    tap.frame("direct", "caller", "c1", "recv", P.ACTOR_RESULT,
              {"t": b"t1"})
    tap.frame("direct", "caller", "c1", "recv", P.ACTOR_RESULT,
              {"t": b"t-never-called"})
    vs = tap.violations()
    assert [v["kind"] for v in vs] == ["response-without-request"]
    v = vs[0]
    assert v["const"] == "ACTOR_RESULT" and v["dir"] == "recv"
    assert v["session"] == "direct" and v["role"] == "caller"
    # Both endpoints' context: our send, the peer's legal reply.
    assert ("send", "ACTOR_CALL") in v["recent"]
    assert ("recv", "ACTOR_RESULT") in v["recent"]


def test_reply_for_unknown_rid_flagged(tap):
    """The worker pipe's rid-keyed request wrapper: a REPLY whose
    req_id was never registered via request_sent() is a response
    without a request."""
    tap.request_sent(P.GET_LOCATIONS, 7)
    tap.frame("worker", "worker", "head", "recv", P.REPLY,
              {"req_id": 7, "result": None})
    assert tap.violations() == []
    tap.frame("worker", "worker", "head", "recv", P.REPLY,
              {"req_id": 8, "result": None})
    kinds = [v["kind"] for v in tap.violations()]
    assert kinds == ["response-without-request"]


def test_stream_item_and_gap_rules(tap):
    tap.frame("direct", "caller", "c1", "send", P.ACTOR_CALL,
              _CALL(b"g1"))
    tap.frame("direct", "caller", "c1", "recv", P.GEN_ITEM,
              {"t": b"g1", "i": 0})
    # Index 2 after 0: a dropped frame, not reordering tolerance.
    tap.frame("direct", "caller", "c1", "recv", P.GEN_ITEM,
              {"t": b"g1", "i": 2})
    # An item for a stream never opened.
    tap.frame("direct", "caller", "c1", "recv", P.GEN_ITEM,
              {"t": b"g-unknown", "i": 0})
    kinds = [v["kind"] for v in tap.violations()]
    assert kinds == ["stream-gap", "stream-item-without-call"]


def test_frame_after_teardown_flagged(tap):
    tap.frame("worker", "head", "h1", "send", P.SHUTDOWN, {})
    tap.frame("worker", "head", "h1", "send", P.EXEC_TASK,
              {"spec": None})
    kinds = [v["kind"] for v in tap.violations()]
    assert "frame-after-teardown" in kinds


def test_wrong_plane_frame_flagged(tap):
    """A worker-pipe constant on a daemon connection is a mux bug."""
    tap.frame("daemon", "daemon", "d1", "send", P.REGISTER_NODE, {})
    tap.frame("daemon", "daemon", "d1", "recv", P.NODE_ACK, {})
    tap.frame("daemon", "daemon", "d1", "recv", P.EXEC_TASK,
              {"spec": None})
    kinds = [v["kind"] for v in tap.violations()]
    assert kinds == ["wrong-plane"]


def test_unmodeled_wire_value_ignored(tap):
    """A msg_type outside the model must be skipped (coverage's
    problem), never crash the hook or spam violations."""
    tap.frame("worker", "head", "h1", "recv", "no-such-wire-value",
              {"x": 1})
    assert tap.violations() == []


# ---------------------------------------------------------------------------
# journal: SIGKILL-safe writes, torn-tail tolerance, report rendering
# ---------------------------------------------------------------------------
def test_journal_written_and_torn_tail_tolerated(tap, tmp_path):
    os.environ["RAY_TPU_WIRETAP_DIR"] = str(tmp_path)
    try:
        tap.frame("direct", "caller", "c1", "send", P.ACTOR_CALL,
                  _CALL(b"t1"))
        tap.frame("direct", "caller", "c1", "recv", P.ACTOR_RESULT,
                  {"t": b"orphan"})
    finally:
        os.environ.pop("RAY_TPU_WIRETAP_DIR", None)
    tap.reset()  # close the journal handle before reading it back
    vs = tap.collect_violations(str(tmp_path))
    assert len(vs) == 1 and vs[0]["kind"] == "response-without-request"
    assert vs[0]["pid"] == os.getpid()
    # A process SIGKILLed mid-write leaves a torn final line; the
    # checker keeps everything before it.
    torn = tmp_path / "wiretap-journal-99999.jsonl"
    torn.write_text(json.dumps({"kind": "stream-gap", "const":
                                "GEN_ITEM", "recent": []}) + "\n"
                    + '{"kind": "frame-after-tear')
    vs = tap.collect_violations(str(tmp_path))
    assert sorted(v["kind"] for v in vs) == ["response-without-request",
                                             "stream-gap"]
    report = tap.format_report(vs)
    assert "PROTOCOL VIOLATION [response-without-request]" in report
    assert "send:ACTOR_CALL" in report  # the ring renders dir:const


# ---------------------------------------------------------------------------
# zero-work guard + end-to-end smoke
# ---------------------------------------------------------------------------
@pytest.mark.perf_smoke
def test_wiretap_off_does_zero_work(shutdown_only):
    """Disabled means ZERO instrumentation work — not 'cheap', zero:
    every record path bumps the ops counter, so a whole init/call/
    shutdown cycle with the tap off must leave it untouched."""
    prev = wiretap.enabled
    wiretap.configure(False)
    try:
        base = wiretap.instrument_ops()
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get(f.remote(1)) == 2
        ray_tpu.shutdown()
        assert wiretap.instrument_ops() == base
    finally:
        wiretap.configure(prev)


def test_cluster_smoke_under_tap(shutdown_only, tmp_path):
    """A real init/actor-call/shutdown cycle under RAY_TPU_WIRETAP=1
    journals zero violations (the protocol-heavy suites run under the
    conftest guard; this is the standalone smoke ci_fast.sh runs)."""
    prev = wiretap.enabled
    prev_dir = os.environ.get("RAY_TPU_WIRETAP_DIR")
    wiretap.reset()
    os.environ["RAY_TPU_WIRETAP_DIR"] = str(tmp_path)
    wiretap.configure(True)
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get([c.incr.remote() for _ in range(3)]) \
            == [1, 2, 3]
        ray_tpu.shutdown()
        wiretap.reset()
        vs = wiretap.collect_violations(str(tmp_path))
        assert vs == [], wiretap.format_report(vs)
    finally:
        wiretap.configure(prev)
        if prev_dir is None:
            os.environ.pop("RAY_TPU_WIRETAP_DIR", None)
        else:
            os.environ["RAY_TPU_WIRETAP_DIR"] = prev_dir
