"""Continuous batching (VERDICT r4 next #8): late requests join a
RUNNING decode batch, slots are reused on completion, and aggregate
throughput beats sequential decoding at 8 concurrent streams.

Correctness anchor: with temperature 0, the continuous engine's output
must be byte-identical to models.generate's sequential path for the
same params (same formulas — per-slot positions and masks are the only
difference)."""

import threading
import time

import numpy as np
import pytest

import jax

from ray_tpu.llm.continuous import ContinuousBatchingEngine
from ray_tpu.llm.serving import ByteTokenizer, LLMEngine
from ray_tpu.models import GPTConfig, gpt_init


@pytest.fixture(scope="module")
def small_setup():
    cfg = GPTConfig(vocab_size=272, d_model=64, n_heads=4, n_layers=2,
                    d_ff=128, max_seq_len=256)
    params = gpt_init(jax.random.PRNGKey(7), cfg)
    return cfg, params


@pytest.fixture()
def engine(small_setup):
    cfg, params = small_setup
    eng = ContinuousBatchingEngine(cfg=cfg, params=params, max_batch=4)
    yield eng
    eng.close()


def _reference(cfg, params, prompt, n):
    return LLMEngine(cfg=cfg, params=params).complete(
        prompt, max_new_tokens=n, temperature=0.0)


class TestCorrectness:
    def test_matches_sequential_reference(self, small_setup, engine):
        cfg, params = small_setup
        out = engine.complete("hello world", 24, 0.0)
        ref = _reference(cfg, params, "hello world", 24)
        assert out == ref

    def test_multiple_prompts_all_match(self, small_setup, engine):
        cfg, params = small_setup
        prompts = ["alpha", "the quick brown fox", "z", "data 123"]
        streams = [engine.submit(p, 16, 0.0) for p in prompts]
        outs = ["".join(s) for s in streams]
        for p, o in zip(prompts, outs):
            assert o == _reference(cfg, params, p, 16), p

    def test_slot_reuse_more_requests_than_slots(self, small_setup,
                                                 engine):
        cfg, params = small_setup
        prompts = [f"prompt {i}" for i in range(10)]  # > max_batch=4
        streams = [engine.submit(p, 8, 0.0) for p in prompts]
        outs = ["".join(s) for s in streams]
        for p, o in zip(prompts, outs):
            assert o == _reference(cfg, params, p, 8), p


class TestLateJoin:
    def test_late_request_joins_running_decode(self, small_setup,
                                               engine):
        cfg, params = small_setup
        long_stream = engine.submit("long running request", 48, 0.0)
        first = []
        # Consume a few tokens so the batch is demonstrably mid-decode.
        it = iter(long_stream)
        for _ in range(6):
            first.append(next(it))
        steps_before = engine.steps
        assert steps_before > 0
        late = "".join(engine.submit("late arrival", 8, 0.0))
        rest = "".join(it)
        # The long request is unaffected by the mid-flight join...
        assert "".join(first) + rest == _reference(
            cfg, params, "long running request", 48)
        # ...the late one is correct...
        assert late == _reference(cfg, params, "late arrival", 8)
        # ...and it decoded on steps AFTER the batch was already
        # running (it joined, it did not restart the engine).
        assert engine.steps > steps_before


class TestThroughput:
    def test_concurrent_beats_sequential_2x(self, small_setup):
        cfg, params = small_setup
        n_streams, n_tokens = 8, 24
        prompts = [f"stream number {i}" for i in range(n_streams)]

        seq = LLMEngine(cfg=cfg, params=params)
        seq.complete("warmup", n_tokens, 0.0)  # compile outside timing

        def time_seq():
            t0 = time.perf_counter()
            for p in prompts:
                seq.complete(p, n_tokens, 0.0)
            return time.perf_counter() - t0

        # Best-of-2 on a shared box: one scheduling hiccup must not
        # decide the comparison.
        t_seq = min(time_seq(), time_seq())

        eng = ContinuousBatchingEngine(cfg=cfg, params=params,
                                       max_batch=n_streams)
        try:
            eng.complete("warmup", n_tokens, 0.0)  # compile
            outs = [None] * n_streams

            def run(i):
                outs[i] = eng.complete(prompts[i], n_tokens, 0.0)

            def time_cb():
                threads = [threading.Thread(target=run, args=(i,))
                           for i in range(n_streams)]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                return time.perf_counter() - t0

            t_cb = min(time_cb(), time_cb())
        finally:
            eng.close()
        for i, p in enumerate(prompts):
            assert outs[i] == _reference(cfg, params, p, n_tokens), p
        speedup = t_seq / t_cb
        assert speedup >= 2.0, (
            f"continuous batching {t_cb:.2f}s vs sequential "
            f"{t_seq:.2f}s -> {speedup:.2f}x (< 2x)")


class TestServeIntegration:
    def test_serve_app_with_continuous_batching(self, ray_start_shared,
                                                small_setup):
        import ray_tpu
        from ray_tpu import serve
        from ray_tpu.llm import build_llm_app

        cfg, params = small_setup
        serve.start()
        app = build_llm_app(cfg=cfg, params=params,
                            continuous_batching=True, max_batch=4)
        serve.run(app, name="cbllm", route_prefix="/cbllm")
        try:
            h = serve.get_deployment_handle("LLMServer", "cbllm")
            out = h.remote({"body": {"prompt": "hi", "max_tokens": 8}}
                           ).result(timeout_s=120)
            assert out["text"] == _reference(cfg, params, "hi", 8)
        finally:
            # Full shutdown (not just delete): later serve tests in the
            # shared session boot their own proxy + controller.
            serve.shutdown()
