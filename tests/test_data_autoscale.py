"""Data autoscaling actor pools + resource-aware streaming backpressure.

Reference: AutoscalingActorPool scale_up/scale_down driven by queued
bundles (data/_internal/execution/operators/actor_pool_map_operator.py:
446,530) and the resource manager + backpressure policies
(execution/resource_manager.py, backpressure_policy/).
"""

import time

import numpy as np
import pytest

import ray_tpu as ray
import ray_tpu.data as rdata
from ray_tpu.data import context as data_context
from ray_tpu.data.dataset import ActorPoolStrategy, _MapBatchesActorPool


@pytest.fixture(scope="module", autouse=True)
def _runtime():
    ray.init(num_cpus=8, ignore_reinit_error=True)
    yield


class _Slow:
    def __call__(self, batch):
        time.sleep(0.3)
        return batch


class _Echo:
    def __call__(self, batch):
        return {k: v * 2 for k, v in batch.items()}


def _pool(min_size, max_size):
    return _MapBatchesActorPool(_Slow, min_size, max_size, {}, (), {})


def test_pool_grows_under_load_and_shrinks_when_drained():
    pool = _pool(1, 3)
    try:
        assert pool.size == 1
        blk = {"x": np.arange(8)}
        refs = [pool.submit(ray.put(blk), None, "numpy", (), {})
                for _ in range(8)]
        # Queue depth (8 outstanding on <=3 actors) must have driven
        # scale-up to max during the submit burst.
        assert pool.size == 3, pool.size
        ray.get(refs)
        # Drained: subsequent submits observe completions and retire
        # idle actors back toward min.
        for _ in range(4):
            ray.get(pool.submit(ray.put(blk), None, "numpy", (), {}))
        assert pool.size < 3, pool.size
    finally:
        pool.shutdown()


def test_map_batches_concurrency_tuple_autoscales_end_to_end():
    ds = rdata.from_items([{"x": i} for i in range(64)]).repartition(16)
    out = ds.map_batches(_Echo, compute=ActorPoolStrategy(
        min_size=1, max_size=3)).take_all()
    assert sorted(r["x"] for r in out) == [2 * i for i in range(64)]


def test_fixed_size_pool_stays_fixed():
    pool = _MapBatchesActorPool(_Echo, 2, 2, {}, (), {})
    try:
        blk = {"x": np.arange(4)}
        refs = [pool.submit(ray.put(blk), None, "numpy", (), {})
                for _ in range(10)]
        assert pool.size == 2
        ray.get(refs)
    finally:
        pool.shutdown()


def test_streaming_backpressure_throttles_under_store_pressure(
        monkeypatch):
    ctx = data_context.DataContext.get_current()
    before = ctx.backpressure_throttle_count
    calls = {"n": 0}

    def fake_stats():
        # High pressure for the first few admission checks, then clear.
        calls["n"] += 1
        return (99, 100) if calls["n"] < 4 else (0, 100)

    from ray_tpu.data import executor as data_executor
    monkeypatch.setattr(data_executor, "_store_stats", fake_stats)
    # No barrier stages: repartition would force bulk execution and
    # bypass the streaming window entirely.
    ds = rdata.range(32, override_num_blocks=8).map_batches(
        lambda b: {"id": b["id"] + 1})
    got = sorted(r["id"] for r in ds.iter_rows())
    assert got == list(range(1, 33))
    assert ctx.backpressure_throttle_count > before


def test_backpressure_off_when_store_quiet():
    ctx = data_context.DataContext.get_current()
    before = ctx.backpressure_throttle_count
    ds = rdata.range(16, override_num_blocks=4).map_batches(
        lambda b: {"id": b["id"]})
    assert len(ds.take_all()) == 16
    assert ctx.backpressure_throttle_count == before
