"""Cross-node transfer: parallel range-pulls and the broadcast tree.

Reference strategy: object manager transfer tests
(src/ray/object_manager/test/object_manager_test.cc chunked transfers;
push_manager.h push scheduling; the 1 GiB broadcast scalability
benchmark in release/benchmarks)."""

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu.cluster_utils import Cluster
from ray_tpu.experimental import broadcast_object


@pytest.fixture(scope="module")
def transfer_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    a = cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    b = cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)
    yield cluster, a, b
    try:
        cluster.shutdown()
    except Exception:
        pass


def test_large_object_parallel_pull(transfer_cluster):
    """A >64MB object crosses nodes via parallel range streams and
    arrives bit-exact."""
    rng = np.random.default_rng(7)
    data = rng.integers(0, 255, size=96 << 20, dtype=np.uint8)  # 96 MB
    ref = ray.put(data)

    @ray.remote(resources={"A": 1})
    def digest(x):
        import hashlib
        return hashlib.sha256(np.ascontiguousarray(x)).hexdigest()

    import hashlib
    expect = hashlib.sha256(data).hexdigest()
    assert ray.get(digest.remote(ref), timeout=180) == expect


def test_broadcast_object_tree(transfer_cluster):
    cluster, a, b = transfer_cluster
    data = np.arange(20 << 20, dtype=np.uint8)  # 20 MB
    ref = ray.put(data)
    n = broadcast_object(ref)
    assert n == 3, n  # head + both daemons hold a copy

    # Tasks on both nodes read the (now-local) copy correctly.
    @ray.remote(resources={"A": 1})
    def sum_a(x):
        return int(x.sum())

    @ray.remote(resources={"B": 1})
    def sum_b(x):
        return int(x.sum())

    expect = int(data.sum())
    assert ray.get(sum_a.remote(ref), timeout=120) == expect
    assert ray.get(sum_b.remote(ref), timeout=120) == expect


def test_broadcast_inline_object_noop(transfer_cluster):
    ref = ray.put(42)  # inline: rides control messages
    assert broadcast_object(ref) == 1
