"""Cross-node transfer: parallel range-pulls, the broadcast tree, and
seeded chaos over the direct object-transfer plane.

Reference strategy: object manager transfer tests
(src/ray/object_manager/test/object_manager_test.cc chunked transfers;
push_manager.h push scheduling; the 1 GiB broadcast scalability
benchmark in release/benchmarks). The chaos tier drives the worker-to-
worker pull fast path (_private/direct.py pull_object) through seeded
injected failures and asserts the daemon-relayed fallback delivers
bit-exact bytes — the test_chaos.py discipline applied to the object
plane. This module runs under BOTH conftest guards (refdebug +
wiretap): every chaos run must also replay to a clean refcount ledger
and a conforming wire-protocol journal."""

import hashlib
import os
import random
import signal
import time

import numpy as np
import pytest

import ray_tpu as ray
from ray_tpu._private import fault
from ray_tpu._private import state as _state
from ray_tpu._private.test_utils import wait_for_condition
from ray_tpu.cluster_utils import Cluster
from ray_tpu.experimental import broadcast_object


@pytest.fixture
def transfer_cluster():
    # Function-scoped on purpose: the autouse refdebug/wiretap guards
    # are per-test, and a cluster outliving them would hand the head
    # DFAs mid-connection (handshake unseen -> spurious violations).
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    a = cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    b = cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)
    yield cluster, a, b
    try:
        cluster.shutdown()
    except Exception:
        pass


def test_large_object_parallel_pull(transfer_cluster):
    """A >64MB object crosses nodes via parallel range streams and
    arrives bit-exact."""
    rng = np.random.default_rng(7)
    data = rng.integers(0, 255, size=96 << 20, dtype=np.uint8)  # 96 MB
    ref = ray.put(data)

    @ray.remote(resources={"A": 1})
    def digest(x):
        import hashlib
        return hashlib.sha256(np.ascontiguousarray(x)).hexdigest()

    import hashlib
    expect = hashlib.sha256(data).hexdigest()
    assert ray.get(digest.remote(ref), timeout=180) == expect


def test_broadcast_object_tree(transfer_cluster):
    cluster, a, b = transfer_cluster
    data = np.arange(20 << 20, dtype=np.uint8)  # 20 MB
    ref = ray.put(data)
    n = broadcast_object(ref)
    assert n == 3, n  # head + both daemons hold a copy

    # Tasks on both nodes read the (now-local) copy correctly.
    @ray.remote(resources={"A": 1})
    def sum_a(x):
        return int(x.sum())

    @ray.remote(resources={"B": 1})
    def sum_b(x):
        return int(x.sum())

    expect = int(data.sum())
    assert ray.get(sum_a.remote(ref), timeout=120) == expect
    assert ray.get(sum_b.remote(ref), timeout=120) == expect


def test_broadcast_inline_object_noop(transfer_cluster):
    ref = ray.put(42)  # inline: rides control messages
    assert broadcast_object(ref) == 1


# ---------------------------------------------------------------------------
# seeded chaos over the direct object-transfer plane
# ---------------------------------------------------------------------------
@pytest.fixture
def chaos_cluster():
    """Per-test cluster slot: the chaos tests need fault configs wired
    in at init, so they cannot share the module cluster (which an
    earlier test may have left up — bring it down first). The tier
    tests the transfer plane itself, so the flag is forced on for the
    spawned nodes regardless of the outer environment — a flag-off
    conformance run must not turn these into vacuous passes (or spurious
    failures on the injection asserts)."""
    ray.shutdown()
    prev = os.environ.get("RAY_TPU_DIRECT_OBJECT_TRANSFER_ENABLED")
    os.environ["RAY_TPU_DIRECT_OBJECT_TRANSFER_ENABLED"] = "1"
    yield
    if prev is None:
        os.environ.pop("RAY_TPU_DIRECT_OBJECT_TRANSFER_ENABLED", None)
    else:
        os.environ["RAY_TPU_DIRECT_OBJECT_TRANSFER_ENABLED"] = prev
    fault.configure(None)
    ray.shutdown()


PULL_CHAOS_SEED = 4242
PULL_CHAOS_CONFIG = {
    "seed": PULL_CHAOS_SEED,
    "rules": [
        # Half the direct-plane pull requests die at the request step:
        # the caller must fall back to the daemon PULL_OBJECT path with
        # bytes intact, invisibly to the reading task.
        {"site": "direct.pull", "action": "raise", "prob": 0.5,
         "exc": "ConnectionError"},
        # A quarter of direct channel dials are dropped — some pulls
        # never even find a channel and go straight to the daemon path.
        {"site": "direct.connect", "action": "drop", "prob": 0.25},
        # The first admission-controlled daemon-path pull in every
        # process fails once: guaranteed retry/backoff coverage on the
        # fallback path itself.
        {"site": "store.pull", "action": "raise", "at": [0],
         "exc": "ConnectionError"},
    ],
}


def test_chaos_seeded_pull_drops_fall_back_bytes_intact(chaos_cluster):
    """Seeded direct-pull and channel-dial failures mid-workload: every
    cross-node read still returns bit-exact bytes (the daemon-relayed
    fallback served the pulls the direct plane dropped), and the
    injections each process performed match the pure (seed, site, seq)
    schedule exactly — the run replays."""
    ray.init(num_cpus=2, fault_config=PULL_CHAOS_CONFIG)
    cluster = Cluster()
    cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

    @ray.remote(resources={"A": 1})
    class Producer:
        def make(self, n, seed):
            rng = np.random.default_rng(seed)
            return rng.integers(0, 255, size=n, dtype=np.uint8)

    @ray.remote(resources={"B": 1})
    class Consumer:
        def pull_digest(self, producer, n, seed):
            # The nested actor call both produces the object on the
            # remote node AND brokers the direct channel the pull fast
            # path rides.
            ref = producer.make.remote(n, seed)
            arr = ray.get(ref, timeout=120)
            return hashlib.sha256(np.ascontiguousarray(arr)).hexdigest()

        def fault_report(self):
            return (fault.injection_log(), fault.site_counts())

    prod = Producer.remote()
    cons = Consumer.remote()
    size = 20 << 20  # 20 MB: spans multiple 8 MB chunks
    for seed in range(6):
        got = ray.get(cons.pull_digest.remote(prod, size + seed, seed),
                      timeout=180)
        rng = np.random.default_rng(seed)
        expect = hashlib.sha256(np.ascontiguousarray(
            rng.integers(0, 255, size=size + seed,
                         dtype=np.uint8))).hexdigest()
        assert got == expect, f"pull {seed} returned corrupt bytes"

    # Determinism: every injection the consumer worker logged is
    # exactly what the pure (seed, site, seq) schedule dictates.
    log, counts = ray.get(cons.fault_report.remote(), timeout=60)
    for site, seq, action in log:
        rule = next(r for r in PULL_CHAOS_CONFIG["rules"]
                    if r["site"] == site)
        if "at" in rule:
            assert seq in rule["at"]
        else:
            draw = random.Random(
                f"{PULL_CHAOS_SEED}:{site}:{seq}").random()
            assert draw < rule["prob"]
    # The fast path was genuinely exercised AND genuinely injected:
    # pulls fired the site, and at least one died there (so at least
    # one of the bit-exact reads above was served by the fallback).
    assert dict(counts).get("direct.pull", 0) >= 1, counts
    assert any(site == "direct.pull" for site, _seq, _a in log), log
    cluster.shutdown()


@pytest.mark.perf_smoke
def test_transfer_disabled_flag_zero_pull_work(chaos_cluster):
    """direct_object_transfer_enabled=false must do ZERO pull-plane
    work — not "cheap", zero: pull_object returns before its op-counter
    bump, proven by a pull_ops() window around a cross-node read (the
    counter-based guard style of test_direct_calls / test_serve_direct).
    The same window with the flag back on counts at least one op, so
    the zero is the flag's doing, not a dead measurement window."""
    ray.init(num_cpus=2)
    cluster = Cluster()
    cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

    @ray.remote(resources={"A": 1})
    class Producer:
        def make(self, n):
            return np.full(n, 7, dtype=np.uint8)

    @ray.remote(resources={"B": 1})
    class Consumer:
        def set_transfer(self, on):
            from ray_tpu._private.config import ray_config
            ray_config.set("direct_object_transfer_enabled", bool(on))

        def warm(self, producer):
            # Brokers the direct channel to the producer's node (the
            # fast path only rides already-brokered channels).
            return int(ray.get(producer.make.remote(8), timeout=60)[0])

        def read_window(self, refs):
            from ray_tpu._private import direct
            before = direct.pull_ops()
            arr = ray.get(refs[0], timeout=120)
            return (direct.pull_ops() - before, int(arr[0]),
                    int(arr.nbytes))

    prod = Producer.remote()
    cons = Consumer.remote()
    assert ray.get(cons.warm.remote(prod), timeout=120) == 7

    size = 4 << 20
    # Flag off: the cross-node read performs zero direct-plane ops.
    ray.get(cons.set_transfer.remote(False), timeout=60)
    ref_off = prod.make.remote(size)
    ray.wait([ref_off], timeout=120)  # produced + location registered
    ops, first, nbytes = ray.get(cons.read_window.remote([ref_off]),
                                 timeout=120)
    assert (first, nbytes) == (7, size)
    assert ops == 0, f"pull plane did {ops} ops while disabled"

    # Same window, flag on: a fresh cross-node read takes the direct
    # pull, so the counter window demonstrably catches real pulls.
    ray.get(cons.set_transfer.remote(True), timeout=60)
    ref_on = prod.make.remote(size)
    ray.wait([ref_on], timeout=120)
    ops, first, nbytes = ray.get(cons.read_window.remote([ref_on]),
                                 timeout=120)
    assert (first, nbytes) == (7, size)
    assert ops >= 1, "direct pull never engaged with the flag on"
    cluster.shutdown()


def test_owner_node_sigkill_mid_pull_typed_object_lost(chaos_cluster):
    """The owning node SIGKILLed while a direct pull is in flight (a
    seeded delay holds the pull at its request step across the kill):
    the read surfaces a typed loss error — not a hang, not a raw socket
    error — after the direct attempt and the daemon fallback both find
    the node gone."""
    ray.init(num_cpus=2, fault_config={
        "seed": 7,
        "rules": [
            # Hold every direct pull at the request step for 2s — the
            # window in which the driver kills the owning node.
            {"site": "direct.pull", "action": "delay", "prob": 1.0,
             "delay_s": 2.0},
        ],
    })
    cluster = Cluster()
    a = cluster.add_node(num_cpus=2, resources={"A": 2}, daemon=True)
    cluster.add_node(num_cpus=2, resources={"B": 2}, daemon=True)

    @ray.remote(resources={"A": 1})
    class Producer:
        def make(self, n):
            return np.ones(n, dtype=np.uint8)

    @ray.remote(resources={"B": 1})
    class Consumer:
        def warm(self, producer):
            # Broker the direct channel to the producer's node.
            return int(ray.get(producer.make.remote(1024),
                               timeout=60)[0])

        def read(self, refs):
            from ray_tpu.exceptions import RayError
            try:
                arr = ray.get(refs[0], timeout=90)
                return ("ok", int(arr.nbytes))
            except RayError as e:
                return (type(e).__name__, str(e)[:200])

    prod = Producer.remote()
    cons = Consumer.remote()
    assert ray.get(cons.warm.remote(prod), timeout=120) == 1
    big = prod.make.remote(64 << 20)
    ray.wait([big], timeout=120)  # produced + location registered

    # Start the read (it parks in the injected delay with the pull
    # outstanding), then SIGKILL the owning node under it.
    fut = cons.read.remote([big])
    time.sleep(0.5)
    os.kill(a.proc.pid, signal.SIGKILL)
    wait_for_condition(lambda: a.proc.poll() is not None, timeout=30)
    rt = _state.current()
    wait_for_condition(
        lambda: a.node_id not in rt.head_server.daemons, timeout=30)

    t0 = time.monotonic()
    kind, detail = ray.get(fut, timeout=180)
    assert kind in ("ObjectLostError", "NodeDiedError"), (kind, detail)
    # Deadline-bounded: the dead channel fails fast (channel_down),
    # it does not wait out the full pull deadline.
    assert time.monotonic() - t0 < 120
    cluster.shutdown()
