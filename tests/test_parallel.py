"""Parallelism strategy tests on the virtual 8-device CPU mesh.

These exercise the net-new layer (SURVEY.md §2.4/§7 phase 5): ring
attention + Ulysses (SP), GPipe pipeline (PP), MoE expert parallel (EP),
each checked for numerical equivalence against the unsharded reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from ray_tpu.parallel.ops import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import mha_reference
from ray_tpu.parallel.moe import moe_layer, top2_gating
from ray_tpu.parallel.pipeline import make_pipelined_fn
from ray_tpu.parallel.sequence import (
    ring_attention,
    sequence_parallel_attention,
    ulysses_attention,
)


def _qkv(b=2, h=4, s=64, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (b, h, s, d)),
            jax.random.normal(ks[1], (b, h, s, d)),
            jax.random.normal(ks[2], (b, h, s, d)))


class TestRingAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_reference(self, sp):
        mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
        q, k, v = _qkv(s=64)
        ref = mha_reference(q, k, v, True)
        out = sequence_parallel_attention(mesh, q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)

    def test_causality_across_shards(self):
        # Mutating the last sequence shard must not affect earlier shards.
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        q, k, v = _qkv(s=32)
        out1 = sequence_parallel_attention(mesh, q, k, v)
        k2 = k.at[:, :, 24:, :].set(7.0)
        v2 = v.at[:, :, 24:, :].set(7.0)
        out2 = sequence_parallel_attention(mesh, q, k2, v2)
        np.testing.assert_allclose(np.asarray(out1[:, :, :24]),
                                   np.asarray(out2[:, :, :24]),
                                   rtol=1e-4, atol=1e-4)


class TestUlysses:
    def test_matches_reference(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        q, k, v = _qkv(s=64)
        ref = mha_reference(q, k, v, True)
        spec = P(None, None, "sp", None)
        fn = shard_map(
            functools.partial(ulysses_attention, axis_name="sp"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        np.testing.assert_allclose(np.asarray(fn(q, k, v)),
                                   np.asarray(ref), rtol=2e-3, atol=2e-3)


class TestPipeline:
    def test_linear_stages(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("pp",))
        n_stages = 4
        ws = jnp.stack([jnp.eye(8) * (i + 1) for i in range(n_stages)])
        pipe = make_pipelined_fn(mesh, lambda w, a: a @ w,
                                 n_microbatches=4)
        x = jax.random.normal(jax.random.PRNGKey(5), (16, 8))
        out = pipe(ws, x)
        expected = np.asarray(x)
        for i in range(n_stages):
            expected = expected @ (np.eye(8) * (i + 1))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4)

    def test_nonlinear_stages(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
        ws = jnp.stack([jnp.full((4, 4), 0.1), jnp.full((4, 4), 0.2)])
        pipe = make_pipelined_fn(mesh, lambda w, a: jnp.tanh(a @ w),
                                 n_microbatches=2)
        x = jax.random.normal(jax.random.PRNGKey(6), (8, 4))
        out = pipe(ws, x)
        expected = np.tanh(np.tanh(np.asarray(x) @ np.full((4, 4), 0.1))
                           @ np.full((4, 4), 0.2))
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4)


class TestMoE:
    def _weights(self, d=8, f=16, e=4):
        gw = jax.random.normal(jax.random.PRNGKey(7), (d, e))
        w1 = jax.random.normal(jax.random.PRNGKey(8), (e, d, f)) * 0.1
        w2 = jax.random.normal(jax.random.PRNGKey(9), (e, f, d)) * 0.1
        return gw, w1, w2

    def test_token_shard_invariance(self):
        # With ample capacity, splitting the token batch must not change
        # routing results (slot-collision regression test).
        gw, w1, w2 = self._weights()
        x = jax.random.normal(jax.random.PRNGKey(10), (32, 8))
        y, _ = moe_layer(x, gw, w1, w2, capacity_factor=8.0)
        y0, _ = moe_layer(x[:16], gw, w1, w2, capacity_factor=8.0)
        y1, _ = moe_layer(x[16:], gw, w1, w2, capacity_factor=8.0)
        np.testing.assert_allclose(
            np.concatenate([np.asarray(y0), np.asarray(y1)]),
            np.asarray(y), atol=1e-5)

    def test_expert_parallel_matches_local(self):
        gw, w1, w2 = self._weights()
        x = jax.random.normal(jax.random.PRNGKey(10), (32, 8))
        y_local, _ = moe_layer(x, gw, w1, w2, capacity_factor=8.0)
        mesh = Mesh(np.array(jax.devices()[:2]), ("ep",))
        fn = shard_map(
            functools.partial(moe_layer, capacity_factor=8.0,
                              axis_name="ep"),
            mesh=mesh, in_specs=(P("ep"), P(), P("ep"), P("ep")),
            out_specs=(P("ep"), P()))
        y_ep, _ = fn(x, gw, w1, w2)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_local),
                                   rtol=1e-3, atol=1e-4)

    def test_capacity_drops(self):
        # With capacity 1 and many tokens, most are dropped -> output is
        # mostly zeros but finite.
        gw, w1, w2 = self._weights()
        x = jax.random.normal(jax.random.PRNGKey(11), (64, 8))
        y, aux = moe_layer(x, gw, w1, w2, capacity_factor=0.05)
        assert np.isfinite(np.asarray(y)).all()
        assert np.isfinite(float(aux))

    def test_gating_slot_uniqueness(self):
        logits = jax.random.normal(jax.random.PRNGKey(12), (16, 4))
        dispatch, combine, _ = top2_gating(logits, capacity=16)
        # No two tokens share an (expert, slot) pair.
        occupancy = np.asarray(dispatch).sum(axis=0)
        assert occupancy.max() <= 1


class TestPipelineTraining:
    """PP that TRAINS: reverse-mode AD of the GPipe scan is the backward
    pipeline; grads must match a single-device sequential model."""

    def _mesh(self, n):
        return Mesh(np.array(jax.devices()[:n]), ("pp",))

    @staticmethod
    def _stage(params, x):
        w, b = params
        return jnp.tanh(x @ w + b)

    def test_grads_match_single_device(self):
        from ray_tpu.parallel.pipeline import make_pipelined_train_fn

        n_stages, n_micro, D = 4, 4, 16
        key = jax.random.PRNGKey(0)
        ws = jax.random.normal(key, (n_stages, D, D)) * 0.5
        bs = jnp.zeros((n_stages, D))
        x = jax.random.normal(jax.random.fold_in(key, 1), (8, D))
        y = jax.random.normal(jax.random.fold_in(key, 2), (8, D))

        def loss_fn(out, y):
            return jnp.mean((out - y) ** 2)

        step = make_pipelined_train_fn(
            self._mesh(n_stages), self._stage, loss_fn, n_micro)
        loss_p, grads_p = step((ws, bs), x, y)

        def seq_loss(params, x, y):
            ws, bs = params
            h = x
            for s in range(n_stages):
                h = self._stage((ws[s], bs[s]), h)
            return loss_fn(h, y)

        loss_s, grads_s = jax.value_and_grad(seq_loss)((ws, bs), x, y)
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=1e-6)
        for a, b in zip(grads_p, grads_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_two_stage_training_converges_like_single_device(self):
        from ray_tpu.parallel.pipeline import make_pipelined_train_fn

        n_stages, n_micro, D = 2, 4, 8
        key = jax.random.PRNGKey(3)
        params = (jax.random.normal(key, (n_stages, D, D)) * 0.3,
                  jnp.zeros((n_stages, D)))
        x = jax.random.normal(jax.random.fold_in(key, 1), (16, D))
        y = jnp.ones((16, D)) * 0.5

        def loss_fn(out, y):
            return jnp.mean((out - y) ** 2)

        step = make_pipelined_train_fn(
            self._mesh(n_stages), self._stage, loss_fn, n_micro)

        def seq_loss(params, x, y):
            ws, bs = params
            h = x
            for s in range(n_stages):
                h = self._stage((ws[s], bs[s]), h)
            return loss_fn(h, y)

        seq_step = jax.jit(jax.value_and_grad(seq_loss))

        lr = 0.5
        p_pipe = p_seq = params
        pipe_losses, seq_losses = [], []
        for _ in range(10):
            lp, gp = step(p_pipe, x, y)
            p_pipe = jax.tree.map(lambda p, g: p - lr * g, p_pipe, gp)
            ls, gs = seq_step(p_seq, x, y)
            p_seq = jax.tree.map(lambda p, g: p - lr * g, p_seq, gs)
            pipe_losses.append(float(lp))
            seq_losses.append(float(ls))
        assert pipe_losses[-1] < pipe_losses[0] * 0.5
        np.testing.assert_allclose(pipe_losses, seq_losses, rtol=1e-4)


class Test1F1B:
    """1F1B pipeline schedule (VERDICT r2 #8): explicit in-schedule
    backward with the activation stash bounded by PIPELINE DEPTH, not
    microbatch count (Megatron-LM non-interleaved 1F1B + activation
    recompute; the reference's users build this from ADAG actor
    pipelines, dag/compiled_dag_node.py:767)."""

    def _mesh(self, n):
        return Mesh(np.array(jax.devices()[:n]), ("pp",))

    @staticmethod
    def _stage(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    @staticmethod
    def _loss(out, y):
        return jnp.mean((out - y) ** 2)

    def _params(self, S, D, key):
        ks = jax.random.split(key, 2)
        return {"w": jax.random.normal(ks[0], (S, D, D)) * 0.5,
                "b": jax.random.normal(ks[1], (S, D)) * 0.1}

    def _seq_loss(self, S):
        def seq(params, x, y):
            h = x
            for s in range(S):
                h = self._stage(
                    {"w": params["w"][s], "b": params["b"][s]}, h)
            return self._loss(h, y)
        return seq

    def test_schedule_properties(self):
        from ray_tpu.parallel.pipeline import one_f1b_schedule
        for S, M in [(2, 4), (4, 8), (3, 7)]:
            act, mb = one_f1b_schedule(S, M)
            T = act.shape[0]
            assert T == 2 * (M + S - 1)  # ideal 1F1B makespan
            for s in range(S):
                live = peak = 0
                for t in range(T):
                    if act[t, s] == 1:
                        live += 1
                    elif act[t, s] == 2:
                        live -= 1
                    peak = max(peak, live)
                # THE 1F1B property: in-flight bounded by depth.
                assert peak <= S - s

    def test_grads_match_single_device(self):
        from ray_tpu.parallel.pipeline import make_1f1b_train_fn

        for S, M in [(2, 4), (4, 8)]:
            D = 16
            key = jax.random.PRNGKey(S * 10 + M)
            params = self._params(S, D, key)
            x = jax.random.normal(jax.random.fold_in(key, 1), (M * 4, D))
            y = jax.random.normal(jax.random.fold_in(key, 2), (M * 4, D))
            step = make_1f1b_train_fn(self._mesh(S), self._stage,
                                      self._loss, M)
            loss_p, grads_p = step(params, x, y)
            loss_s, grads_s = jax.value_and_grad(
                self._seq_loss(S))(params, x, y)
            np.testing.assert_allclose(float(loss_p), float(loss_s),
                                       rtol=1e-5)
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(jax.device_get(grads_p[k])),
                    np.asarray(grads_s[k]), rtol=1e-4, atol=1e-6)

    def test_lower_peak_memory_than_gpipe(self):
        """VERDICT done-when: lower peak live activations than GPipe at
        M=8, S=4 (XLA-reported temp allocation of the compiled step)."""
        from ray_tpu.parallel.pipeline import (make_1f1b_train_fn,
                                               make_pipelined_train_fn)

        S, M, D = 4, 8, 256
        mesh = self._mesh(S)
        params = {"w": jnp.zeros((S, D, D)), "b": jnp.zeros((S, D))}
        x = jnp.zeros((M * 32, D))
        y = jnp.zeros((M * 32, D))
        f1 = make_1f1b_train_fn(mesh, self._stage, self._loss, M)
        fg = make_pipelined_train_fn(mesh, self._stage, self._loss, M)
        m1 = f1.lower(params, x, y).compile().memory_analysis()
        mg = fg.lower(params, x, y).compile().memory_analysis()
        t1 = getattr(m1, "temp_size_in_bytes", None)
        tg = getattr(mg, "temp_size_in_bytes", None)
        if t1 is None or tg is None:
            pytest.skip("backend reports no memory analysis")
        assert t1 < tg, (t1, tg)


class TestMultiSlice:
    """DCN / multi-slice mesh: slices emulated as contiguous CPU device
    groups (SURVEY §4 CPU-mirror); batch shards over (dp_dcn, dp) so the
    gradient reduction is hierarchical (ICI within slice, DCN across)."""

    def test_multislice_train_step_matches_single_mesh(self):
        import dataclasses

        from ray_tpu.models import GPTConfig, make_train_step
        from ray_tpu.models.gpt import shard_batch
        from ray_tpu.parallel import (
            MeshConfig,
            dcn_rules,
            make_mesh,
            make_multislice_mesh,
            tp_rules,
        )

        cfg = dataclasses.replace(GPTConfig.tiny(), remat=False)
        tokens = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, 32), dtype=np.int32)
        batch_np = (tokens, np.roll(tokens, -1, axis=1))

        # 2 emulated slices x (dp=2, tp=2)
        ms_mesh = make_multislice_mesh(
            MeshConfig(dp=2, tp=2), devices=jax.devices()[:8],
            num_slices=2)
        assert ms_mesh.shape["dp_dcn"] == 2
        init_ms, step_ms = make_train_step(cfg, mesh=ms_mesh,
                                           rules=dcn_rules())
        state_ms = init_ms(jax.random.PRNGKey(0))
        batch_ms = shard_batch(
            tuple(jnp.asarray(x) for x in batch_np), ms_mesh,
            axis=("dp_dcn", "dp"))
        state_ms, m_ms = step_ms(state_ms, batch_ms)

        # same model on a flat single-slice mesh
        flat = make_mesh(MeshConfig(dp=4, tp=2),
                         devices=jax.devices()[:8])
        init_f, step_f = make_train_step(cfg, mesh=flat,
                                         rules=tp_rules())
        state_f = init_f(jax.random.PRNGKey(0))
        batch_f = shard_batch(
            tuple(jnp.asarray(x) for x in batch_np), flat)
        state_f, m_f = step_f(state_f, batch_f)

        np.testing.assert_allclose(float(m_ms["loss"]),
                                   float(m_f["loss"]), rtol=1e-5)

    def test_slice_count_cpu_is_one(self):
        from ray_tpu.parallel import slice_count
        assert slice_count() == 1


class TestFSDP:
    """ZeRO-style param sharding (VERDICT r1: 'no test demonstrates
    reduce-scatter grad flow / memory win vs DP')."""

    def test_fsdp_params_sharded_and_loss_matches_dp(self):
        import dataclasses

        from ray_tpu.models import GPTConfig, make_train_step
        from ray_tpu.models.gpt import shard_batch
        from ray_tpu.parallel import (
            MeshConfig,
            fsdp_rules,
            make_mesh,
            tp_rules,
        )

        cfg = dataclasses.replace(GPTConfig.tiny(), remat=False)
        tokens = np.random.default_rng(1).integers(
            0, cfg.vocab_size, (8, 32), dtype=np.int32)
        batch_np = (tokens, np.roll(tokens, -1, axis=1))

        fsdp_mesh = make_mesh(MeshConfig(dp=2, fsdp=4),
                              devices=jax.devices()[:8])
        init_f, step_f = make_train_step(cfg, mesh=fsdp_mesh,
                                         rules=fsdp_rules())
        state_f = init_f(jax.random.PRNGKey(0))
        # Memory win: weight matrices are PHYSICALLY sharded over fsdp —
        # each device holds 1/4 of every embed-axis weight (and so do the
        # adam moments, which mirror param shardings).
        w = state_f["params"]["layers"][0]["w1"]
        assert "fsdp" in str(w.sharding.spec), w.sharding.spec
        local = w.addressable_shards[0].data.shape
        assert local[0] == w.shape[0] // 4, (local, w.shape)
        moments = [x for x in jax.tree.leaves(state_f["opt_state"])
                   if hasattr(x, "sharding") and x.shape == w.shape]
        assert moments and all(
            "fsdp" in str(m.sharding.spec) for m in moments)

        batch_f = shard_batch(
            tuple(jnp.asarray(x) for x in batch_np), fsdp_mesh)
        state_f, metrics_f = step_f(state_f, batch_f)

        # Same model, pure DP: losses must match (fsdp only re-lays-out
        # params; the math is identical).
        dp_mesh = make_mesh(MeshConfig(dp=8), devices=jax.devices()[:8])
        init_d, step_d = make_train_step(cfg, mesh=dp_mesh,
                                         rules=tp_rules())
        state_d = init_d(jax.random.PRNGKey(0))
        batch_d = shard_batch(
            tuple(jnp.asarray(x) for x in batch_np), dp_mesh)
        state_d, metrics_d = step_d(state_d, batch_d)
        # f32 reduction order differs between layouts: ~1e-4 band.
        np.testing.assert_allclose(float(metrics_f["loss"]),
                                   float(metrics_d["loss"]), rtol=1e-3)

    def test_fsdp_grad_flow_uses_reduce_scatter(self):
        """The gradient reduction over sharded params must lower to
        reduce-scatter (+ all-gather for param use), not a full
        all-reduce of unsharded grads — the ZeRO traffic shape."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("dp", "fsdp"))
        w_sh = NamedSharding(mesh, P("fsdp", None))
        x_sh = NamedSharding(mesh, P("dp", None))

        def loss(w, x):
            return jnp.sum(jnp.tanh(x @ w) ** 2)

        w = jax.device_put(jnp.ones((64, 64), jnp.float32), w_sh)
        x = jax.device_put(jnp.ones((16, 64), jnp.float32), x_sh)
        grad_fn = jax.jit(jax.grad(loss), out_shardings=w_sh)
        hlo = grad_fn.lower(w, x).compile().as_text()
        # TPU fuses this to a reduce-scatter op; the CPU backend lowers
        # the same semantics as all-reduce + dynamic-slice (scatter by
        # slicing). Either way the grads must come back SHARDED (the
        # ZeRO property: no device materializes the full gradient).
        assert ("reduce-scatter" in hlo
                or ("all-reduce" in hlo and "dynamic-slice" in hlo)), hlo
        g = grad_fn(w, x)
        assert "fsdp" in str(g.sharding.spec)
        assert g.addressable_shards[0].data.shape[0] == g.shape[0] // 4
