"""Worker-lease pipelining vs blocking tasks: no deadlocks.

Reference semantics: a worker blocked in ray.get releases its CPU to
the raylet so dependency tasks can schedule (the classic nested-task
deadlock mitigation), and pipelined-but-unstarted tasks must not be
pinned behind a blocked task forever (here: RECALL_QUEUED evacuation).
"""

import time

import pytest

import ray_tpu as rt


@pytest.fixture(scope="module", autouse=True)
def _one_cpu_runtime():
    # ONE CPU: the hardest case — any blocked grant-holder starves
    # everyone unless blocked workers release their grant. Owns its
    # runtime: a shared 4-CPU runtime would mask the starvation, and
    # leaking a 1-CPU runtime breaks later modules' resource
    # assertions.
    rt.shutdown()
    rt.init(num_cpus=1)
    yield
    rt.shutdown()


def test_nested_get_on_full_cluster_completes():
    @rt.remote
    def child():
        return 21

    @rt.remote
    def parent():
        return rt.get(child.remote()) * 2

    # parent holds the only CPU and blocks on child: the blocked lease
    # must release its grant so child can run.
    assert rt.get(parent.remote(), timeout=60) == 42


@rt.remote(num_cpus=0, max_concurrency=2)
class _Gate:
    def __init__(self):
        self._open = False

    def open(self):
        self._open = True
        return True

    def wait_open(self):
        while not self._open:
            time.sleep(0.02)
        return 7


@rt.remote
def _victim():
    return 42


@rt.remote
def _parent(gate):
    return rt.get(gate.wait_open.remote())


def test_victims_run_while_parent_blocked():
    gate = _Gate.remote()
    p = _parent.remote(gate)
    time.sleep(1.0)  # parent is now blocked in get on the gate call
    # Victims submitted AFTER the block: the blocked worker is not a
    # pipeline target and its grant is released, so they must complete
    # while the parent still blocks.
    vs = [_victim.remote() for _ in range(3)]
    assert rt.get(vs, timeout=30) == [42] * 3
    rt.get(gate.open.remote())
    assert rt.get(p, timeout=30) == 7


def test_victims_evacuate_when_queued_before_block():
    gate = _Gate.remote()
    p = _parent.remote(gate)
    # Victims submitted IMMEDIATELY: they may pipeline behind the
    # parent before it blocks; once it blocks, the queue must be
    # recalled and re-dispatched instead of waiting on the gate.
    vs = [_victim.remote() for _ in range(3)]
    assert rt.get(vs, timeout=30) == [42] * 3
    rt.get(gate.open.remote())
    assert rt.get(p, timeout=30) == 7
