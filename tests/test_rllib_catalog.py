"""Model-catalog tests (reference strategy: rllib/core/models tests —
Catalog encoder choice per obs space + model-config plumbing, plus an
image-obs learning smoke test)."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DQNConfig, PPOConfig
from ray_tpu.rllib.core.catalog import (
    Catalog, ConvEncoder, MLPEncoder, MODEL_DEFAULTS, default_conv_filters,
    encoder_out_dim, merge_model_config)
from ray_tpu.rllib.core.rl_module import DQNModule, PPOModule, SACModule


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


class TinyImageEnv:
    """8x8x3 image obs; the dominant brightness encodes the rewarded
    action — learnable only if pixels actually reach the policy."""

    def __init__(self, config=None):
        import gymnasium as gym
        self.observation_space = gym.spaces.Box(
            0.0, 1.0, (8, 8, 3), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._bright = 0

    def _obs(self):
        self._bright = int(self._rng.integers(0, 2))
        img = np.full((8, 8, 3), 0.8 if self._bright else 0.2, np.float32)
        img += self._rng.normal(0, 0.05, img.shape).astype(np.float32)
        return np.clip(img, 0.0, 1.0).astype(np.float32)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        rew = 1.0 if int(action) == self._bright else 0.0
        self._t += 1
        return self._obs(), rew, self._t >= 16, False, {}

    def close(self):
        pass


class MemoryEnv:
    """Cue shown only at t=0; reward at the last step for recalling it —
    a feed-forward policy caps at 0.5, an LSTM can hit 1.0."""

    def __init__(self, config=None):
        import gymnasium as gym
        self.observation_space = gym.spaces.Box(
            -1.0, 1.0, (2,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._rng = np.random.default_rng(0)
        self.T = 5

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._cue = int(self._rng.integers(0, 2))
        self._t = 0
        return np.array([2 * self._cue - 1, 0.0], np.float32), {}

    def step(self, action):
        self._t += 1
        last = self._t >= self.T
        rew = (1.0 if int(action) == self._cue else 0.0) if last else 0.0
        obs = np.array([0.0, self._t / self.T], np.float32)
        return obs, rew, last, False, {}

    def close(self):
        pass


class TestCatalogUnits:
    def test_default_conv_filters_shrink_to_4px(self):
        filters = default_conv_filters((64, 64, 3))
        assert len(filters) == 4  # 64 -> 32 -> 16 -> 8 -> 4
        assert filters[0][0] == 16 and filters[-1][0] == 128
        assert all(stride == 2 for _, _, stride in filters)
        # Tiny inputs still get one mixing conv.
        assert default_conv_filters((4, 4, 1)) == ((16, 3, 1),)

    def test_encoder_choice(self):
        assert isinstance(Catalog.build_encoder((17,)), MLPEncoder)
        assert isinstance(Catalog.build_encoder((8, 8, 3)), ConvEncoder)
        # conv_filters=[] explicitly disables the CNN.
        enc = Catalog.build_encoder((8, 8, 3), {"conv_filters": []})
        assert isinstance(enc, MLPEncoder)

    def test_unknown_model_key_rejected(self):
        with pytest.raises(ValueError, match="conv_filers"):
            merge_model_config({"conv_filers": [[16, 4, 2]]})

    def test_model_defaults_merge(self):
        cfg = merge_model_config({"fcnet_hiddens": [32, 32]})
        assert cfg["fcnet_hiddens"] == [32, 32]
        assert cfg["fcnet_activation"] == MODEL_DEFAULTS["fcnet_activation"]

    def test_encoder_out_dim(self):
        enc = Catalog.build_encoder(
            (8, 8, 3), {"post_fcnet_hiddens": [96]})
        assert encoder_out_dim(enc, (8, 8, 3)) == 96
        mlp = Catalog.build_encoder((17,), {"fcnet_hiddens": [48, 24]})
        assert encoder_out_dim(mlp, (17,)) == 24


class TestModulesWithImages:
    def test_ppo_module_conv_params(self):
        mod = PPOModule((8, 8, 3), 2)
        assert mod.preserve_obs_shape
        params = mod.init_params(0)
        flat = str(params)
        assert "Conv" in flat
        obs = np.random.default_rng(0).random((5, 8, 8, 3), np.float32)
        acts = mod.forward_inference(params, obs)
        assert acts.shape == (5,)
        acts, info = mod.forward_exploration(
            params, obs, np.random.default_rng(1))
        assert acts.shape == (5,) and "vf_preds" in info

    def test_dqn_module_image(self):
        mod = DQNModule((8, 8, 3), 3)
        params = mod.init_params(0)
        obs = np.zeros((4, 8, 8, 3), np.float32)
        assert mod.forward_inference(params, obs).shape == (4,)

    def test_sac_module_image(self):
        import jax
        mod = SACModule((8, 8, 3), 2)
        params = mod.init_params(0)
        obs = np.zeros((4, 8, 8, 3), np.float32)
        act = mod.forward_inference(params, obs)
        assert act.shape == (4, 2)
        q1, q2 = mod.apply_q(params, obs, act)
        assert q1.shape == (4,) and q2.shape == (4,)
        a, logp = mod.sample_action(params, obs, jax.random.PRNGKey(0))
        assert a.shape == (4, 2) and logp.shape == (4,)

    def test_pickle_roundtrip_keeps_model_config(self):
        import pickle
        mod = PPOModule((8, 8, 3), 2,
                        model_config={"post_fcnet_hiddens": [64]})
        clone = pickle.loads(pickle.dumps(mod))
        assert clone.obs_shape == (8, 8, 3)
        assert clone.model_config == {"post_fcnet_hiddens": [64]}
        assert clone.preserve_obs_shape

    def test_vector_module_param_config(self):
        mod = PPOModule(6, 3, model_config={
            "fcnet_hiddens": [32], "fcnet_activation": "relu"})
        assert mod.hidden == (32,)
        assert not mod.preserve_obs_shape
        params = mod.init_params(0)
        obs = np.zeros((2, 6), np.float32)
        assert mod.forward_inference(params, obs).shape == (2,)


class TestLSTM:
    def test_lstm_encoder_step_matches_seq(self):
        import jax
        import jax.numpy as jnp
        from ray_tpu.rllib.core.catalog import LSTMEncoder
        enc = LSTMEncoder(encoder=MLPEncoder((32,)), cell_size=16)
        x = jnp.asarray(
            np.random.default_rng(0).random((2, 5, 6)), jnp.float32)
        carry = enc.initial_carry(2)
        resets = jnp.zeros((2, 5))
        params = enc.init(jax.random.PRNGKey(0), x, carry, resets)
        feats, _ = enc.apply(params, x, carry, resets)
        assert feats.shape == (2, 5, 16)
        # chaining T=1 steps reproduces the full scan
        f2, cr = [], enc.initial_carry(2)
        for t in range(5):
            ft, cr = enc.apply(params, x[:, t:t + 1], cr,
                               resets[:, t:t + 1])
            f2.append(ft[:, 0])
        assert np.allclose(feats, np.stack(f2, 1), atol=1e-5)
        # a reset at t cuts history: suffix equals a fresh start
        r = resets.at[:, 2].set(1.0)
        fr, _ = enc.apply(params, x, carry, r)
        ff, _ = enc.apply(params, x[:, 2:], carry, resets[:, 2:])
        assert np.allclose(fr[:, 2:], ff, atol=1e-5)

    def test_use_lstm_rejected_outside_ppo(self):
        with pytest.raises(NotImplementedError, match="use_lstm"):
            DQNModule(4, 2, model_config={"use_lstm": True})
        with pytest.raises(NotImplementedError, match="use_lstm"):
            SACModule(4, 2, model_config={"use_lstm": True})

    def test_recurrent_module_state_lifecycle(self):
        from ray_tpu.rllib.core.rl_module import RecurrentPPOModule
        mod = RecurrentPPOModule(4, 2, model_config={
            "use_lstm": True, "lstm_cell_size": 8, "fcnet_hiddens": [16]})
        params = mod.init_params(0)
        rng = np.random.default_rng(0)
        obs = rng.random((1, 4)).astype(np.float32)
        _, info = mod.forward_exploration(params, obs, rng)
        for k in ("vf_preds", "action_logp", "state_in_c", "state_in_h",
                  "state_out_c", "state_out_h"):
            assert k in info, k
        # first step starts from zero state...
        assert np.allclose(info["state_in_c"], 0.0)
        # ...the second consumes the first's output state
        _, info2 = mod.forward_exploration(params, obs, rng)
        assert np.allclose(info2["state_in_c"], info["state_out_c"])
        assert not np.allclose(info2["state_in_c"], 0.0)
        mod.on_episode_end()
        _, info3 = mod.forward_exploration(params, obs, rng)
        assert np.allclose(info3["state_in_c"], 0.0)

    def test_chunk_fragments(self):
        from ray_tpu.rllib.algorithms.ppo import _chunk_fragments
        t0, cell = 7, 3
        frag = {
            "rewards": np.arange(t0, dtype=np.float32),
            "obs": np.arange(t0 * 2, dtype=np.float32).reshape(t0, 2),
            "actions": np.zeros(t0, np.int64),
            "advantages": np.ones(t0, np.float32),
            "value_targets": np.ones(t0, np.float32),
            "action_logp": np.zeros(t0, np.float32),
            "terminateds": np.array(
                [False, False, True, False, False, False, False]),
            "truncateds": np.zeros(t0, bool),
            "state_in_c": np.arange(t0 * cell,
                                    dtype=np.float32).reshape(t0, cell),
            "state_in_h": np.zeros((t0, cell), np.float32),
        }
        out = _chunk_fragments([frag], max_seq_len=4)
        assert out["obs"].shape == (2, 4, 2)
        # done at t=2 -> reset before t=3 (row 0, pos 3)
        assert out["resets"][0].tolist() == [0.0, 0.0, 0.0, 1.0]
        # chunk 2 starts at t=4 with its recorded rollout carry
        assert np.allclose(out["carry_c"][1], frag["state_in_c"][4])
        # 3-step tail padded, mask marks real rows
        assert out["mask"][1].tolist() == [1.0, 1.0, 1.0, 0.0]
        assert np.allclose(out["obs"][1, 3], 0.0)

    def test_ppo_lstm_memory_env_learns(self):
        algo = (PPOConfig()
                .environment(MemoryEnv)
                .env_runners(num_env_runners=2,
                             rollout_fragment_length=100)
                .training(lr=3e-3, gamma=0.99, num_epochs=4,
                          minibatch_size=80,
                          model={"use_lstm": True, "lstm_cell_size": 32,
                                 "max_seq_len": 10,
                                 "fcnet_hiddens": [32]})
                .debugging(seed=0)
                .build())
        try:
            for _ in range(10):
                result = algo.train()
            assert result["episode_return_mean"] > 0.8
            ev = algo.evaluate(num_episodes=10)
            # Chance is 0.5; only a policy that REMEMBERS the cue can
            # approach 1.0.
            assert ev["evaluation_return_mean"] >= 0.9
        finally:
            algo.stop()


class TestImageTraining:
    def test_ppo_image_env_trains(self):
        algo = (PPOConfig()
                .environment(TinyImageEnv)
                .env_runners(num_env_runners=1,
                             rollout_fragment_length=256)
                .training(lr=3e-3, gamma=0.9, num_epochs=6,
                          minibatch_size=64,
                          model={"conv_filters": [[8, 3, 2]],
                                 "post_fcnet_hiddens": [32]})
                .debugging(seed=0)
                .build())
        try:
            for _ in range(8):
                result = algo.train()
            assert "total_loss" in result
            # Random policy scores ~8/16; a CNN that sees the pixels
            # should be clearly above chance within a few iterations.
            ev = algo.evaluate(num_episodes=5)
            assert ev["evaluation_return_mean"] > 10.0
        finally:
            algo.stop()

    def test_dqn_image_env_step(self):
        algo = (DQNConfig()
                .environment(TinyImageEnv)
                .env_runners(num_env_runners=1,
                             rollout_fragment_length=64)
                .training(lr=1e-3,
                          model={"conv_filters": [[8, 3, 2]],
                                 "post_fcnet_hiddens": [32]})
                .debugging(seed=0)
                .build())
        try:
            result = algo.train()
            assert result["training_iteration"] == 1
        finally:
            algo.stop()
