"""Control/data-plane transport tests: multi-message framing, writer
coalescing, per-connection ordering, and the host copy gate.

The perf_smoke-marked test is the syscall-count regression guard: a
burst of N messages through a ConnectionWriter must ship in a handful
of vectored writes, never one write per message (wall-clock-free, so it
can run in tier-1 without flaking on loaded machines)."""

import pickle
import socket
import threading
import time

import pytest

from ray_tpu._private import protocol as P
from ray_tpu._private.netcomm import ConnectionWriter, HostCopyGate


class _FakeConn:
    """Socket wrapper quacking like multiprocessing.Connection for the
    writer (fileno only)."""

    def __init__(self, sock):
        self._sock = sock

    def fileno(self):
        return self._sock.fileno()


def _drain_messages(sock, timeout=5.0):
    """Read until EOF; return the decoded message list."""
    parser = P.FrameParser()
    sock.settimeout(timeout)
    while True:
        try:
            chunk = sock.recv(1 << 20)
        except OSError:
            break
        if not chunk:
            break
        parser.feed(chunk)
    return list(parser.messages())


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------
def test_dump_load_messages_round_trip():
    msgs = [("alpha", {"x": 1, "nested": {"a": [1, 2, 3]}}),
            ("beta", {"blob": b"\x00" * 1000}),
            ("gamma", {"empty": None})]
    body = b"".join(bytes(c) for c in P.dump_messages(msgs))
    assert P.is_batch(body)
    assert P.load_messages(body) == msgs


def test_single_message_passthrough():
    data = P.dump_message("solo", {"k": 7})
    assert not P.is_batch(data)
    assert P.load_messages(data) == [("solo", {"k": 7})]


def test_out_of_band_buffers_round_trip():
    big = bytes(range(256)) * 512  # 128 KiB
    msgs = [("carry", {"frame": pickle.PickleBuffer(big), "tag": 3}),
            ("plain", {"y": 2})]
    chunks = P.dump_messages(msgs)
    # The big buffer must NOT be copied into the pickle stream: it rides
    # as its own chunk of the vectored write.
    assert any(getattr(c, "nbytes", len(c)) == len(big) for c in chunks)
    body = b"".join(bytes(c) for c in chunks)
    out = P.load_messages(body)
    assert out[0][0] == "carry"
    assert bytes(out[0][1]["frame"]) == big
    assert out[1] == ("plain", {"y": 2})


def test_frame_parser_handles_arbitrary_splits():
    msgs = [("m", {"i": i, "pad": b"x" * (i * 37 % 500)})
            for i in range(40)]
    # Two frames: one batch, one classic single message.
    batch = b"".join(bytes(c) for c in P.dump_messages(msgs[:39]))
    single = P.dump_message(*msgs[39])
    import struct
    stream = (struct.pack("!i", len(batch)) + batch
              + struct.pack("!i", len(single)) + single)
    for step in (1, 3, 7, 64, 1000, len(stream)):
        parser = P.FrameParser()
        got = []
        for i in range(0, len(stream), step):
            parser.feed(stream[i:i + step])
            got.extend(parser.messages())
        assert got == msgs, f"split={step}"


# ---------------------------------------------------------------------------
# writer coalescing / ordering
# ---------------------------------------------------------------------------
@pytest.mark.perf_smoke
def test_writer_burst_costs_few_writes():
    """N queued messages must arrive in <= k writes (syscall-count
    based, not wall-clock): the regression guard against falling back
    to one-write-per-message."""
    a, b = socket.socketpair()
    try:
        w = ConnectionWriter(_FakeConn(a), autostart=False)
        n = 100
        for i in range(n):
            w.send_message("burst", {"i": i})
        shipped = w.drain_once()
        assert shipped == n
        # One coalesced vectored write for the whole burst (IOV_MAX
        # chunking could legitimately split it; allow a small k).
        assert w.write_calls <= 3, w.write_calls
        a.close()
        got = _drain_messages(b)
        assert [p["i"] for _t, p in got] == list(range(n))
    finally:
        a.close()
        b.close()


def test_writer_strict_fifo_across_threads():
    """Per-connection ordering: the wire order must match enqueue
    order exactly, including under concurrent senders (each thread's
    own sequence must arrive as a subsequence in order)."""
    a, b = socket.socketpair()
    try:
        w = ConnectionWriter(_FakeConn(a))
        per, nthreads = 200, 4

        def sender(tid):
            for i in range(per):
                w.send_message("t", {"tid": tid, "i": i})

        threads = [threading.Thread(target=sender, args=(t,))
                   for t in range(nthreads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert w.flush(5.0)
        w.close()
        a.close()
        got = _drain_messages(b)
        assert len(got) == per * nthreads
        seen = {t: -1 for t in range(nthreads)}
        for _t, p in got:
            assert p["i"] == seen[p["tid"]] + 1, "per-sender order broken"
            seen[p["tid"]] = p["i"]
    finally:
        a.close()
        b.close()


def test_writer_partial_writes_survive_small_sndbuf():
    """Force partial writev results (tiny SO_SNDBUF + big payloads) and
    assert every byte still lands in order."""
    a, b = socket.socketpair()
    a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16 * 1024)
    try:
        w = ConnectionWriter(_FakeConn(a))
        payload = b"z" * 40_000
        got_msgs = []
        done = threading.Event()

        def reader():
            got_msgs.extend(_drain_messages(b, timeout=10.0))
            done.set()

        rt = threading.Thread(target=reader, daemon=True)
        rt.start()
        for i in range(20):
            w.send_message("big", {"i": i, "pad": payload})
        assert w.flush(10.0)
        w.close()
        a.close()
        assert done.wait(10.0)
        assert [p["i"] for _t, p in got_msgs] == list(range(20))
        assert all(p["pad"] == payload for _t, p in got_msgs)
    finally:
        a.close()
        b.close()


def test_writer_empty_oob_buffer_does_not_spin():
    """A zero-length out-of-band buffer must neither hang the writev
    loop nor corrupt framing."""
    a, b = socket.socketpair()
    try:
        w = ConnectionWriter(_FakeConn(a))
        w.send_message("empty", {"buf": pickle.PickleBuffer(b""), "i": 1})
        w.send_message("after", {"i": 2})
        assert w.flush(5.0)
        w.close()
        a.close()
        got = _drain_messages(b)
        assert [t for t, _p in got] == ["empty", "after"]
        assert bytes(got[0][1]["buf"]) == b""
    finally:
        a.close()
        b.close()


def test_writer_latches_error_and_raises():
    a, b = socket.socketpair()
    w = ConnectionWriter(_FakeConn(a))
    b.close()
    a.shutdown(socket.SHUT_RDWR)
    # Writes eventually fail; later sends must raise, not hang.
    deadline = time.monotonic() + 5.0
    raised = False
    while time.monotonic() < deadline:
        try:
            w.send_message("x", {"pad": b"p" * 65536})
        except OSError:
            raised = True
            break
        time.sleep(0.01)
    a.close()
    assert raised, "writer never surfaced the broken pipe"


# ---------------------------------------------------------------------------
# loop writer (selector-drained ConnectionWriter)
# ---------------------------------------------------------------------------
class _FakeLoop:
    """Quacks like ControlLoop for a LoopWriter whose drains the test
    runs by hand (the test thread plays the loop thread)."""

    def on_loop_thread(self):
        return False

    def arm_writer(self, writer):
        pass


def test_loop_writer_pending_bytes_balance():
    """Accounting symmetry: _pending_bytes is credited with payload
    bytes at drain-start and debited with raw wrote (which includes
    conn_frame_header/batch framing) — the framing delta must be
    credited too, or every completed batch drifts the queued_bytes()
    gauge negative and silently loosens the backpressure threshold."""
    from ray_tpu._private.netcomm import LoopWriter
    a, b = socket.socketpair()
    try:
        w = LoopWriter(_FakeConn(a), _FakeLoop())
        # Single-message frame path (header + body).
        w.send_message("one", {"i": 1})
        assert w._drain_nonblocking() == "idle"
        assert w.queued_bytes() == 0, w.queued_bytes()
        assert w._pending_bytes == 0, w._pending_bytes
        # Batch frame path (assemble_batch adds per-message framing).
        for i in range(20):
            w.send_message("burst", {"i": i, "pad": b"x" * 100})
        assert w._drain_nonblocking() == "idle"
        assert w.queued_bytes() == 0, w.queued_bytes()
        assert w._pending_bytes == 0, w._pending_bytes
        a.close()
        got = _drain_messages(b)
        assert len(got) == 21
    finally:
        a.close()
        b.close()


def test_loop_writer_send_on_loop_thread_never_blocks():
    """Deadlock guard: the loop thread is a LoopWriter's SOLE drainer,
    so an inline handler sending on its own loop (the head's NODE_PING
    -> NODE_SYNC ack) must enqueue past the high-water mark instead of
    blocking — against a zero-window peer a blocking wait could never
    be satisfied and the whole loop shard would wedge."""
    from ray_tpu._private.netcomm import ControlLoop, LoopWriter
    loop = ControlLoop(name="test-loop")
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        w = LoopWriter(_FakeConn(a), loop, max_queued_bytes=8192)
        # Stalled peer: b never reads. Push one message far past the
        # high-water mark (the check happens at entry, so a fresh
        # sender slips a large chunk through) — the loop parks the
        # overflow in _pending and _pending_bytes stays > max.
        w.send_message("big", {"pad": b"z" * (256 << 10)})
        sent_on_loop = threading.Event()

        def on_msgs(ctx, msgs):
            # Runs ON the loop thread, writer saturated: must return,
            # not block.
            w.send_message("sync", {"ok": True})
            sent_on_loop.set()

        loop.register_conn(_FakeConn(a), w, on_msgs, lambda ctx: None,
                           None)
        # Wait until the loop parked the overflow (writer saturated).
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and w._pending_bytes <= 8192:
            time.sleep(0.01)
        assert w._pending_bytes > 8192, "loop never parked the overflow"
        # Poke the loop: one inbound frame -> on_msgs on the loop
        # thread -> send_message on the saturated writer.
        body = P.dump_message("ping", {})
        import struct
        b.sendall(struct.pack("!i", len(body)) + body)
        assert sent_on_loop.wait(5.0), (
            "loop-thread send blocked on its own writer's backpressure "
            "(shard deadlock)")
        # The loop thread is still alive and draining: release the
        # stall and everything lands.
        b.settimeout(5.0)
        parser = P.FrameParser()
        types = []
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                chunk = b.recv(1 << 20)
            except OSError:
                break
            if not chunk:
                break
            parser.feed(chunk)
            types.extend(t for t, _p in parser.messages())
            if "sync" in types:
                break
        assert types and types[0] == "big" and "sync" in types, types
    finally:
        loop.stop()
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# host copy gate
# ---------------------------------------------------------------------------
def test_copy_gate_width_and_fifo():
    gate = HostCopyGate(width=2, max_wait_s=10.0)
    lock = threading.Lock()
    admitted, active, max_active = [], [0], [0]

    def worker(i):
        with gate:
            with lock:
                admitted.append(i)
                active[0] += 1
                max_active[0] = max(max_active[0], active[0])
            time.sleep(0.02)
            with lock:
                active[0] -= 1

    threads = []
    for i in range(8):
        t = threading.Thread(target=worker, args=(i,))
        t.start()
        threads.append(t)
        time.sleep(0.005)  # deterministic enqueue order
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "gate starved a waiter"
    assert len(admitted) == 8          # everyone made progress
    assert max_active[0] <= 2          # width honored
    assert admitted == sorted(admitted)  # FIFO admission


def test_copy_gate_all_progress_under_contention():
    """M threads hammering the gate all complete (no starvation) and
    total throughput is bounded by width, not by one."""
    gate = HostCopyGate(width=2, max_wait_s=30.0)
    done = []
    lock = threading.Lock()

    def worker(i):
        for _ in range(5):
            with gate:
                time.sleep(0.002)
        with lock:
            done.append(i)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert sorted(done) == list(range(6))


def test_copy_gate_timeout_runs_ungated():
    gate = HostCopyGate(width=1, max_wait_s=0.1)
    hold = threading.Event()
    release = threading.Event()

    def holder():
        gate.acquire()
        hold.set()
        release.wait(10.0)
        gate.release()

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert hold.wait(5.0)
    t0 = time.monotonic()
    admitted = gate.acquire()   # queue is full: times out to ungated
    took = time.monotonic() - t0
    gate.release()
    release.set()
    t.join(timeout=5)
    assert not admitted          # fell back to ungated
    assert took < 5.0            # and did not wedge


def test_put_gate_thresholds():
    from ray_tpu._private.netcomm import _NullGate
    from ray_tpu._private.object_store import _put_gate
    assert isinstance(_put_gate(1024), _NullGate)
    big = 512 * (1 << 20)
    assert isinstance(_put_gate(big), HostCopyGate)
