"""llm batch stages, chaos fault injection, and client-server tests
(reference strategy: llm/tests/batch, python/ray/tests/test_chaos.py,
util/client tests)."""
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def _cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


# -- llm batch stages -------------------------------------------------------
def test_llm_stage_units():
    from ray_tpu.llm import (ChatTemplateStage, DetokenizeStage,
                             GPTInferenceStage, TokenizeStage)
    batch = {"messages": [[{"role": "user", "content": "hi"}]]}
    out = ChatTemplateStage()(batch)
    assert "<|user|>: hi" in out["prompt"][0]
    out = TokenizeStage()(out)
    assert out["tokens"][0].dtype == np.int32
    out = GPTInferenceStage(max_new_tokens=4)(out)
    assert out["generated_tokens"][0].shape == (4,)
    out = DetokenizeStage()(out)
    assert isinstance(out["generated_text"][0], str)


def test_llm_processor_over_dataset():
    from ray_tpu import data
    from ray_tpu.llm import ProcessorConfig, build_processor
    ds = data.from_items([{"prompt": f"hello world {i}"}
                          for i in range(8)])
    processor = build_processor(ProcessorConfig(batch_size=4,
                                                max_new_tokens=2))
    # skip chat template: rows already have "prompt"
    out = processor(ds).take_all()
    assert len(out) == 8
    assert all("generated_text" in row for row in out)


# -- chaos ------------------------------------------------------------------
def test_task_retry_under_worker_kills():
    """Tasks survive SIGKILLed workers via retries (reference:
    test_chaos.py + WorkerKillerActor)."""
    from ray_tpu._private.test_utils import WorkerKiller

    @ray_tpu.remote(max_retries=3)
    def slow(i):
        time.sleep(0.4)
        return i * 2

    refs = [slow.remote(i) for i in range(12)]
    killer = WorkerKiller(kill_interval_s=0.3, max_kills=2,
                          warmup_s=0.2).run()
    out = ray_tpu.get(refs, timeout=120)
    killed = killer.stop()
    assert out == [i * 2 for i in range(12)]
    assert len(killed) >= 1  # chaos actually happened


def test_actor_restart_under_kills():
    from ray_tpu._private.test_utils import WorkerKiller, wait_for_condition

    @ray_tpu.remote(max_restarts=2, max_task_retries=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            time.sleep(0.1)
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote()) == 1
    killer = WorkerKiller(target_actors=True, kill_interval_s=0.2,
                          max_kills=1, warmup_s=0.0).run()
    wait_for_condition(lambda: len(killer.killed) >= 1, timeout=15)
    killer.stop()
    # restarted actor serves again (state reset: fresh instance)
    val = ray_tpu.get(c.bump.remote(), timeout=60)
    assert val >= 1


# -- client-server ----------------------------------------------------------
def test_client_server_roundtrip():
    from ray_tpu.util import client as client_mod
    host, port = client_mod.server.serve("127.0.0.1", 0)
    conn = client_mod.connect(f"{host}:{port}")

    def double(x):
        return x * 2

    rf = conn.remote(double)
    ref = rf.remote(21)
    assert conn.get(ref) == 42

    data_ref = conn.put([1, 2, 3])
    rf2 = conn.remote(lambda xs: sum(xs))
    assert conn.get(rf2.remote(data_ref)) == 6  # ref args resolve

    class Acc:
        def __init__(self, base):
            self.v = base

        def add(self, x):
            self.v += x
            return self.v

    ac = conn.remote(Acc)
    h = ac.remote(10)
    assert conn.get(h.add.remote(5)) == 15
    assert conn.get(h.add.remote(1)) == 16  # stateful
    conn.close()


def test_client_from_separate_process():
    """The real thing: a different PROCESS drives the cluster through
    the client server."""
    from ray_tpu._private import state
    from ray_tpu.util import client as client_mod
    host, port = client_mod.server.serve("127.0.0.1", 0)
    token_hex = state.current().cluster_token.hex()
    code = f"""
import sys
sys.path.insert(0, {repr(sys.path[0])})
from ray_tpu.util import client
conn = client.connect("{host}:{port}", token="{token_hex}")
rf = conn.remote(lambda x: x ** 2)
print("result:", conn.get(rf.remote(9)))
conn.close()
"""
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120)
    assert "result: 81" in out.stdout, out.stderr[-2000:]


def test_cluster_survives_driver_exit():
    """The head can run as a STANDALONE process (`ray_tpu start`);
    drivers are clients whose exit does not take the cluster down
    (VERDICT r1 missing #7's 'driver crash = cluster gone' concern: the
    driver is not the head in this deployment shape). Per-session actors
    release on disconnect like the reference's; DETACHED actors' survival
    across HEAD restarts is covered in test_oom_spill.py."""
    head_code = """
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")  # env alone is overridable
import ray_tpu
from ray_tpu._private import state
from ray_tpu.util.client import server
ray_tpu.init(num_cpus=2)
host, port = server.serve("127.0.0.1", 0)
print(f"ADDR {host}:{port} TOKEN "
      f"{state.current().cluster_token.hex()}", flush=True)
while True:
    time.sleep(60)  # killed by the test's finally
""" % sys.path[0]
    head = subprocess.Popen([sys.executable, "-c", head_code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)

    def run_driver(body: str, marker: str, addr: str, token: str):
        code = f"""
import sys
sys.path.insert(0, {sys.path[0]!r})
from ray_tpu.util import client
conn = client.connect({addr!r}, token={token!r})
{body}
print({marker!r}, flush=True)
conn.close()
"""
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=180)
        assert marker in out.stdout, out.stderr[-1500:]

    try:
        # Bounded banner wait: a wedged head must fail, not hang pytest.
        import threading
        banner = {}

        def _read():
            banner["line"] = head.stdout.readline().strip()

        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(timeout=120)
        line = banner.get("line", "")
        if not line.startswith("ADDR"):
            head.kill()
            raise AssertionError(f"head never started: {line!r}")
        _, addr, _, token = line.split()
        # Drain further head stdout so log streaming can't fill the
        # 64 KB pipe and block the head mid-test.
        import threading as _threading
        _threading.Thread(target=lambda: head.stdout.read(),
                          daemon=True).start()

        # Driver 1: create a stateful actor, bump it, EXIT.
        run_driver("""
class Acc:
    def __init__(self):
        self.n = 0
    def add(self, x):
        self.n += x
        return self.n
handle = conn.remote(Acc).remote()
assert conn.get(handle.add.remote(5)) == 5
assert conn.get(handle.add.remote(3)) == 8  # stateful within session
""", "driver1 ok", addr, token)

        # Driver 1 exited; the head still serves driver 2 with fresh work
        # (per-session actors are released on disconnect — reference
        # semantics; DETACHED lifetimes survive, which
        # test_detached_actor_respawns_after_head_restart covers).
        run_driver("""
rf = conn.remote(lambda x: x * 10)
assert conn.get(rf.remote(7)) == 70
""", "driver2 ok", addr, token)
    finally:
        head.kill()
        head.wait(timeout=10)
