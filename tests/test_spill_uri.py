"""Object spilling through the pyarrow-fs URI seam.

Reference: spilling to URI targets including S3
(src/ray/raylet/local_object_manager.* + spill workers configured via
object_spilling_config). The seam is exercised with file:// — the same
pyarrow.fs code path gs:// and s3:// take.
"""

import os

import numpy as np
import pytest

from ray_tpu._private.config import ray_config
from ray_tpu._private.ids import ObjectID


@pytest.fixture()
def uri_spill(tmp_path, monkeypatch):
    target = tmp_path / "bucket"
    target.mkdir()
    monkeypatch.setitem(ray_config._values, "object_spilling_path",
                        f"file://{target}")
    yield str(target)


def _arena(tmp_path, capacity):
    pytest.importorskip("ray_tpu._native")
    from ray_tpu import _native
    if not _native.available():
        pytest.skip("native store unavailable")
    from ray_tpu._private.object_store import ArenaObjectStore
    return ArenaObjectStore(str(tmp_path / "store"), capacity=capacity)


def test_spill_restore_roundtrip_through_uri(tmp_path, uri_spill):
    store = _arena(tmp_path, capacity=4 << 20)
    try:
        payloads = {}
        # Overflow a tiny arena: earlier objects must spill to the URI.
        for i in range(6):
            oid = ObjectID.from_random()
            data = np.full(1 << 20, i, dtype=np.uint8)
            store.put(oid, data)
            payloads[oid] = data
        stats = store.stats()
        assert stats["spilled_count"] > 0, stats
        # Spilled bytes landed under the URI target, not the local dir.
        spilled_files = []
        for root, _dirs, files in os.walk(uri_spill):
            spilled_files += files
        assert spilled_files, "nothing written through the pyarrow.fs seam"
        # Every object restores with correct bytes, wherever it lives.
        for oid, data in payloads.items():
            got = store.get(oid)
            assert np.array_equal(got, data), int(data[0])
    finally:
        store.shutdown()


def test_uri_spill_free_deletes_remote_copy(tmp_path, uri_spill):
    store = _arena(tmp_path, capacity=4 << 20)
    try:
        oids = []
        for i in range(4):
            oid = ObjectID.from_random()
            store.put(oid, np.full(1 << 20, i, dtype=np.uint8))
            oids.append(oid)
        n_before = sum(len(f) for _r, _d, f in os.walk(uri_spill))
        assert n_before > 0
        for oid in oids:
            store.free(oid)
        n_after = sum(len(f) for _r, _d, f in os.walk(uri_spill))
        assert n_after == 0, n_after
    finally:
        store.shutdown()


def test_shutdown_cleans_uri_target(tmp_path, uri_spill):
    store = _arena(tmp_path, capacity=4 << 20)
    for i in range(4):
        store.put(ObjectID.from_random(),
                  np.full(1 << 20, i, dtype=np.uint8))
    store.shutdown()
    leftovers = [f for _r, _d, fs in os.walk(uri_spill) for f in fs]
    assert not leftovers, leftovers


def test_file_store_uri_spill_roundtrip(tmp_path, uri_spill):
    from ray_tpu._private.object_store import ObjectStore
    store = ObjectStore(str(tmp_path / "fstore"), capacity=4 << 20)
    try:
        payloads = {}
        for i in range(6):
            oid = ObjectID.from_random()
            data = np.full(1 << 20, i, dtype=np.uint8)
            store.put(oid, data)
            payloads[oid] = data
        assert store.stats()["spilled_count"] > 0
        files = [f for _r, _d, fs in os.walk(uri_spill) for f in fs]
        assert files, "file store never wrote through the URI seam"
        for oid, data in payloads.items():
            assert np.array_equal(store.get(oid), data)
    finally:
        store.shutdown()
